"""bench.py's parent is the tunnel-discipline layer the round's
evidence depends on; its recovery path (a later series phase hangs →
the embed headline still gets reported, marked partial) must not
regress.  Driven as a real subprocess the way the driver/watcher run
it, with the BENCH_TEST_SLEEP_AFTER hook standing in for the round-3
on-chip hang."""
from __future__ import annotations

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lock_refusal_instead_of_second_client(tmp_path):
    """ADVICE r3: with the watcher's flock held for the whole window,
    bench.py must FAIL with an error JSON — never start a child that
    would be a second concurrent tunnel client."""
    import fcntl

    lock_path = tmp_path / "watch.lock"
    holder = open(lock_path, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    env = dict(
        os.environ,
        SPTPU_BENCH_LOCK=str(lock_path),
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_TIMEOUT="75",
    )
    env.pop("BENCH_CPU", None)        # CPU mode would skip the lock
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    holder.close()
    assert proc.returncode == 0
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] == 0.0
    assert "lock not acquired" in rec["error"]
    assert rec["detail"]["attempts"] == 0     # no child ever spawned


def test_starved_window_promotes_ledger_headline(tmp_path):
    """VERDICT r4 #1a: when the window is starved but the ledger holds
    a real TPU measurement, the headline must be that measurement (with
    provenance + series_complete=false), never 0.0."""
    import fcntl

    import time as _time

    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps({
        "metric": "embeddings_per_sec_per_chip", "value": 1990.8,
        "unit": "embeddings/s", "vs_baseline": 0.1593,
        "ts": _time.strftime("%Y-%m-%dT%H:%M:%S%z",
                             _time.localtime(_time.time() - 3600)),
        "detail": {"backend": "tpu", "bucket": 64, "batch": 512},
    }) + "\n")
    lock_path = tmp_path / "watch.lock"
    holder = open(lock_path, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    env = dict(
        os.environ,
        SPTPU_BENCH_LOCK=str(lock_path),
        SPTPU_BENCH_LEDGER=str(ledger),
        BENCH_TIMEOUT="75",
    )
    env.pop("BENCH_CPU", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    holder.close()
    assert proc.returncode == 0
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] == 1990.8
    assert rec["vs_baseline"] == 0.1593
    assert "error" not in rec                 # a real number, not a failure
    assert rec["series_complete"] is False    # watcher keeps knocking
    assert rec["detail"]["headline_from_ledger"] is True
    assert rec["detail"]["ledger_detail"]["backend"] == "tpu"
    assert "window_error" in rec["detail"]
    assert rec["detail"]["ledger_age_h"] < 2


def test_stale_ledger_record_not_promoted(tmp_path):
    """A measurement older than ~a round (BENCH_PROMOTE_MAX_AGE_H) is
    cross-round history, not this round's headline: report 0.0 with the
    record as context only."""
    import fcntl
    import time as _time

    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps({
        "metric": "embeddings_per_sec_per_chip", "value": 1990.8,
        "unit": "embeddings/s", "vs_baseline": 0.1593,
        "ts": _time.strftime("%Y-%m-%dT%H:%M:%S%z",
                             _time.localtime(_time.time() - 100 * 3600)),
        "detail": {"backend": "tpu"},
    }) + "\n")
    lock_path = tmp_path / "watch.lock"
    holder = open(lock_path, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    env = dict(
        os.environ,
        SPTPU_BENCH_LOCK=str(lock_path),
        SPTPU_BENCH_LEDGER=str(ledger),
        BENCH_TIMEOUT="75",
    )
    env.pop("BENCH_CPU", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    holder.close()
    assert proc.returncode == 0
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] == 0.0
    assert "error" in rec
    assert rec["detail"]["last_measured"]["value"] == 1990.8
    assert rec["detail"]["last_measured_age_h"] > 90


def test_driver_flag_lifecycle(tmp_path):
    """The per-pid driver-priority flag (<lock>.driver.<pid>) must
    exist while the driver bench waits on the watcher's lock and be
    gone afterwards."""
    import fcntl
    import glob
    import threading
    import time as _time

    lock_path = tmp_path / "watch.lock"
    flag_glob = str(tmp_path / "watch.lock.driver.*")
    holder = open(lock_path, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    env = dict(
        os.environ,
        SPTPU_BENCH_LOCK=str(lock_path),
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_TIMEOUT="70",
    )
    env.pop("BENCH_CPU", None)
    seen_flag = threading.Event()

    def _watch_flag():
        for _ in range(600):
            if glob.glob(flag_glob):
                seen_flag.set()
                return
            _time.sleep(0.1)

    th = threading.Thread(target=_watch_flag)
    th.start()
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    th.join()
    holder.close()
    assert proc.returncode == 0
    assert seen_flag.is_set()                 # flag was up during the run
    assert not glob.glob(flag_glob)           # and removed on exit


def test_crash_at_window_end_recovers_fresh_headline(tmp_path):
    """A child that crashes after the embed phase ledgered (rc!=0, no
    retry fits the window) is a FRESH in-window measurement: the parent
    must report it via the recovery file (interrupted series), never
    via the cross-window ledger-promotion path (which the watcher reads
    as 'no fresh claim' and naps on)."""
    env = dict(
        os.environ,
        BENCH_CPU="1",
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_PHASES="embed,profile",
        BENCH_TEST_CRASH_AFTER="embed",      # crash EVERY attempt,
                                             # after the headline lands
        BENCH_TEXTS="8", BENCH_BATCH="4", BENCH_BUCKETS="32",
        BENCH_P50_PROBES="2",
        BENCH_TIMEOUT="100", BENCH_ATTEMPT_TIMEOUT="80",
        BENCH_BACKOFF="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=200)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] > 0
    assert rec["series_complete"] is False
    assert "interrupted_at" in rec
    assert "headline_from_ledger" not in rec.get("detail", {})


def test_crashed_series_retry_is_partial(tmp_path):
    """ADVICE r4 (medium): after a begun-series crash, the embed-only
    retry must report series_complete=false (+ phases_restricted) even
    though every phase it was ASKED to run succeeded."""
    env = dict(
        os.environ,
        BENCH_CPU="1",
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_PHASES="embed",
        BENCH_TEST_CRASH_AT="embed",
        BENCH_TEST_CRASH_ONCE=str(tmp_path / "crashed.flag"),
        BENCH_TEXTS="8", BENCH_BATCH="4", BENCH_BUCKETS="32",
        BENCH_P50_PROBES="2",
        BENCH_TIMEOUT="320", BENCH_ATTEMPT_TIMEOUT="150",
        BENCH_BACKOFF="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=340)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] > 0
    assert rec["series_complete"] is False
    assert rec["phases_restricted"] == "embed"


def test_timeout_recovers_headline(tmp_path):
    env = dict(
        os.environ,
        BENCH_CPU="1",
        SPTPU_BENCH_LEDGER=str(tmp_path / "ledger.jsonl"),
        BENCH_PHASES="embed,profile",
        BENCH_TEST_SLEEP_AFTER="embed",      # profile never runs
        BENCH_TEXTS="8", BENCH_BATCH="4", BENCH_BUCKETS="32",
        BENCH_P50_PROBES="2",
        # the first attempt must fit a cold-cache jax compile of the
        # embed phase plus the timed drains (ADVICE r4): 150 s attempt
        # budget keeps the recovery path deterministic on a slow host
        BENCH_TIMEOUT="320", BENCH_ATTEMPT_TIMEOUT="150",
        BENCH_BACKOFF="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=340)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    rec = json.loads(line)
    # the headline survived the hang, marked as an interrupted series
    assert rec["metric"] == "embeddings_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["series_complete"] is False
    assert "error" not in rec
    # and the ledger holds the embed record the child appended itself
    led = [json.loads(ln) for ln in
           (tmp_path / "ledger.jsonl").read_text().splitlines()]
    assert [r["metric"] for r in led] == ["embeddings_per_sec_per_chip"]
