"""Span tracer (utils/trace.py): aggregation, thread safety, no-op
cost path, and heartbeat integration."""
from __future__ import annotations

import json
import threading
import time

import numpy as np

from libsplinter_tpu.utils.trace import Tracer


def test_disabled_tracer_is_noop():
    t = Tracer(enabled=False)
    with t.span("x"):
        pass
    assert t.snapshot() == {}
    # disabled spans share one context object (no per-call allocation)
    assert t.span("a") is t.span("b")


def test_span_aggregation():
    t = Tracer(enabled=True)
    for _ in range(3):
        with t.span("work"):
            time.sleep(0.002)
    snap = t.snapshot()
    assert snap["work"]["n"] == 3
    assert snap["work"]["total_ms"] >= 5
    assert snap["work"]["max_ms"] >= snap["work"]["total_ms"] / 3 - 1e-6
    t.reset()
    assert t.snapshot() == {}


def test_span_thread_safety():
    t = Tracer(enabled=True)

    def worker():
        for _ in range(200):
            with t.span("w"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.snapshot()["w"]["n"] == 1600


def test_embedder_heartbeat_carries_spans(tmp_path, monkeypatch):
    from libsplinter_tpu import Store, T_VARTEXT
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine import embedder as emod

    monkeypatch.setattr(emod.tracer, "enabled", True)
    emod.tracer.reset()
    name = f"/spt-trace-{tmp_path.name}"
    Store.unlink(name)
    # max_val must hold the full heartbeat: counters (incl. the commit
    # pipeline's) + the span table + the quantiles section
    st = Store.create(name, nslots=64, max_val=4096, vec_dim=8)
    try:
        emb = emod.Embedder(st, encoder_fn=lambda ts: np.zeros(
            (len(ts), 8), np.float32), max_ctx=64)
        emb.attach()
        st.set("k", "text")
        st.set_type("k", T_VARTEXT)
        st.label_or("k", P.LBL_EMBED_REQ)
        emb.run_once()
        emb.publish_stats()
        snap = json.loads(st.get(P.KEY_EMBED_STATS).rstrip(b"\0"))
        assert "spans" in snap
        assert snap["spans"]["embed.drain"]["n"] >= 1
        assert snap["spans"]["embed.commit"]["n"] >= 1
        # histogram-sourced quantiles ride the same heartbeat under
        # the PIPELINE_STAGES names (prefix stripped)
        assert "quantiles" in snap
        assert snap["quantiles"]["commit"]["n"] >= 1
        for k in ("p50_ms", "p90_ms", "p99_ms", "max_ms"):
            assert k in snap["quantiles"]["commit"], k
    finally:
        st.close()
        Store.unlink(name)
