"""Decoder LM: KV-cache correctness, sampler chain, byte tokenizer."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models import (ByteTokenizer, CompletionModel,
                                    DecoderConfig, init_cache, sample_top_p)
from libsplinter_tpu.models.decoder import Decoder

CFG = DecoderConfig.tiny(dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return CompletionModel(CFG, buckets=(16, 32), temp=0.0)


def test_prefill_then_decode_matches_full_forward(model):
    """Bucketed prefill + N single-token decode steps must produce the
    same logits as one full causal forward over the whole sequence."""
    rng = np.random.default_rng(0)
    seq = rng.integers(3, CFG.vocab_size, size=14).astype(np.int32)
    P = 9

    # incremental: prefill 9, decode tokens 9..13
    logits = model.prefill(seq[:P])
    inc = [logits]
    for t in seq[P:]:
        inc.append(model.decode_one(int(t)))
    model.reset()

    # one-shot reference: full causal forward, no padding
    mod = Decoder(CFG)
    cache = init_cache(CFG, 1)
    full, _ = mod.apply(model.params, jnp.asarray(seq[None, :]), cache,
                        jnp.int32(0))
    full = np.asarray(full[0])

    # inc[i] is the prediction after consuming seq[:P+i]
    for i, got in enumerate(inc):
        np.testing.assert_allclose(got, full[P - 1 + i], rtol=2e-4,
                                   atol=2e-4)


def test_prefill_bucket_padding_is_invisible(model):
    """The same prompt through different bucket sizes gives identical
    logits — pad rows in the KV cache never become visible."""
    prompt = np.arange(3, 13).astype(np.int32)     # len 10 → bucket 16
    a = model.prefill(prompt)
    model.reset()
    big = CompletionModel(CFG, buckets=(32,), params=model.params,
                          temp=0.0)
    b = big.prefill(prompt)                        # len 10 → bucket 32
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


def test_decode_position_tracking(model):
    model.prefill(np.ones(5, np.int32))
    assert model.pos == 5
    model.decode_one(7)
    assert model.pos == 6
    model.reset()
    assert model.pos == 0
    with pytest.raises(RuntimeError):
        model.decode_one(1)


def test_sampler_greedy_and_top_p():
    logits = jnp.asarray(np.array([0.0, 5.0, 1.0, -2.0], np.float32))
    key = jax.random.PRNGKey(0)
    assert int(sample_top_p(key, logits, temp=0.0)) == 1
    # dominant token holds ~97% mass: top_p=0.5 nucleus is {1} alone
    for i in range(20):
        k = jax.random.PRNGKey(i)
        assert int(sample_top_p(k, logits, top_p=0.5, temp=1.0)) == 1


def test_sampler_top_p_excludes_tail():
    """Tokens outside the nucleus must never be drawn."""
    logits = jnp.asarray(np.array([4.0, 4.0, -10.0, -10.0], np.float32))
    seen = {int(sample_top_p(jax.random.PRNGKey(i), logits,
                             top_p=0.9, temp=1.0)) for i in range(50)}
    assert seen <= {0, 1}
    assert len(seen) == 2          # both nucleus members reachable


def test_byte_tokenizer_round_trip():
    tok = ByteTokenizer()
    text = "Hello, wörld! ☃"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == text
    # streaming pieces concatenate to the same bytes
    pieces = b"".join(tok.token_to_piece(i) for i in ids)
    assert pieces.decode("utf-8") == text
    assert tok.encode("abc", max_len=2) == [tok.bos_id, 3 + ord("a")]


def test_prompt_longer_than_largest_bucket():
    """A prompt between the largest bucket and max_len must still land
    in a program (regression: broadcast crash for bucket < P < max_len)."""
    m = CompletionModel(CFG, buckets=(16,), temp=0.0)
    assert m.buckets[-1] == CFG.max_len
    logits = m.prefill(np.ones(40, np.int32))      # 16 < 40 < 128
    assert logits.shape == (CFG.vocab_size,)
    assert m.pos == 40


def test_byte_tokenizer_out_of_range_ids_are_empty():
    """Lm-head slack rows (vocab wider than the byte table) must stream
    as empty pieces, not crash (regression: ValueError in bytes())."""
    tok = ByteTokenizer()
    assert tok.token_to_piece(300) == b""
    assert tok.token_to_piece(tok.pad_id) == b""
    assert tok.decode([1, 3 + ord("a"), 5000, 2]) == "a"


def test_context_window_guard(model):
    with pytest.raises(ValueError):
        model.prefill(np.ones(CFG.max_len, np.int32))
    with pytest.raises(ValueError):
        model.prefill(np.zeros(0, np.int32))


def test_safetensors_round_trip(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from libsplinter_tpu.models.decoder import (
        CompletionModel, Decoder, DecoderConfig, export_safetensors_params,
        init_cache, load_safetensors_params,
    )
    cfg = DecoderConfig.tiny(dtype=jnp.float32)
    module = Decoder(cfg)
    cache = init_cache(cfg, 1)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32), cache, jnp.int32(0))
    path = str(tmp_path / "lm.safetensors")
    export_safetensors_params(params, cfg, path)
    loaded = load_safetensors_params(path, cfg)

    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(loaded)
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (pa, va), (_, vb) in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(va, np.float32),
                                   np.asarray(vb, np.float32),
                                   err_msg=str(pa))

    # a model built from the checkpoint produces identical logits
    a = CompletionModel(cfg, params=params, temp=0.0)
    b = CompletionModel(cfg, weights=path, temp=0.0)
    prompt = np.arange(1, 9, dtype=np.int32)
    np.testing.assert_allclose(a.prefill(prompt), b.prefill(prompt),
                               rtol=1e-6)


def test_tied_lm_head_fallback(tmp_path):
    import jax.numpy as jnp
    import numpy as np
    from safetensors import safe_open
    from safetensors.numpy import save_file
    from libsplinter_tpu.models.decoder import (
        DecoderConfig, load_safetensors_params,
    )
    cfg = DecoderConfig.tiny(dtype=jnp.float32)
    # build a full checkpoint then strip lm_head to simulate tied weights
    import jax
    from libsplinter_tpu.models.decoder import (
        Decoder, export_safetensors_params, init_cache,
    )
    params = Decoder(cfg).init(jax.random.PRNGKey(1),
                               jnp.zeros((1, 8), jnp.int32),
                               init_cache(cfg, 1), jnp.int32(0))
    full = str(tmp_path / "full.safetensors")
    export_safetensors_params(params, cfg, full)
    with safe_open(full, framework="np") as f:
        kept = {k: f.get_tensor(k) for k in f.keys() if k != "lm_head.weight"}
    tied = str(tmp_path / "tied.safetensors")
    save_file(kept, tied)
    loaded = load_safetensors_params(tied, cfg)
    np.testing.assert_allclose(
        np.asarray(loaded["params"]["lm_head"]["kernel"]),
        np.asarray(loaded["params"]["tok_emb"]["embedding"]).T)


def test_generate_tokens_eos_stop(model):
    """With eos_id set the generator stops right after yielding it; the
    chunk's speculative tail is not surfaced (ADVICE r2)."""
    # greedy tiny model: find whatever token it repeats, use it as "eos"
    toks = []
    for t in model.generate_tokens(np.ones(4, np.int32), 12, chunk=4):
        toks.append(t)
    model.reset()
    eos = toks[2]                      # appears mid-stream
    got = list(model.generate_tokens(np.ones(4, np.int32), 12, chunk=4,
                                     eos_id=eos))
    model.reset()
    assert got[-1] == eos
    assert eos not in got[:-1]
    assert got == toks[: toks.index(eos) + 1]


def test_chunk_program_tracks_sampler_settings(model):
    """Mutating top_p/temp after first use must not silently reuse the
    stale compiled program (ADVICE r2): the cache is keyed on them."""
    model.prefill(np.ones(4, np.int32))
    model.decode_chunk(1, 4)
    n_before = len(model._chunk_progs)
    old = (model.top_p, model.temp)
    try:
        model.top_p, model.temp = 0.5, 1.3
        model.reset()
        model.prefill(np.ones(4, np.int32))
        model.decode_chunk(1, 4)
        assert len(model._chunk_progs) == n_before + 1
        keys = set(model._chunk_progs)
        assert (4, 1, 0.5, 1.3) in keys    # (n, bp, top_p, temp)
    finally:
        model.top_p, model.temp = old
        model.reset()
