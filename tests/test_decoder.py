"""Decoder LM: KV-cache correctness, sampler chain, byte tokenizer."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models import (ByteTokenizer, CompletionModel,
                                    DecoderConfig, init_cache, sample_top_p)
from libsplinter_tpu.models.decoder import Decoder

CFG = DecoderConfig.tiny(dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return CompletionModel(CFG, buckets=(16, 32), temp=0.0)


def test_prefill_then_decode_matches_full_forward(model):
    """Bucketed prefill + N single-token decode steps must produce the
    same logits as one full causal forward over the whole sequence."""
    rng = np.random.default_rng(0)
    seq = rng.integers(3, CFG.vocab_size, size=14).astype(np.int32)
    P = 9

    # incremental: prefill 9, decode tokens 9..13
    logits = model.prefill(seq[:P])
    inc = [logits]
    for t in seq[P:]:
        inc.append(model.decode_one(int(t)))
    model.reset()

    # one-shot reference: full causal forward, no padding
    mod = Decoder(CFG)
    cache = init_cache(CFG, 1)
    full, _ = mod.apply(model.params, jnp.asarray(seq[None, :]), cache,
                        jnp.int32(0))
    full = np.asarray(full[0])

    # inc[i] is the prediction after consuming seq[:P+i]
    for i, got in enumerate(inc):
        np.testing.assert_allclose(got, full[P - 1 + i], rtol=2e-4,
                                   atol=2e-4)


def test_prefill_bucket_padding_is_invisible(model):
    """The same prompt through different bucket sizes gives identical
    logits — pad rows in the KV cache never become visible."""
    prompt = np.arange(3, 13).astype(np.int32)     # len 10 → bucket 16
    a = model.prefill(prompt)
    model.reset()
    big = CompletionModel(CFG, buckets=(32,), params=model.params,
                          temp=0.0)
    b = big.prefill(prompt)                        # len 10 → bucket 32
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


def test_decode_position_tracking(model):
    model.prefill(np.ones(5, np.int32))
    assert model.pos == 5
    model.decode_one(7)
    assert model.pos == 6
    model.reset()
    assert model.pos == 0
    with pytest.raises(RuntimeError):
        model.decode_one(1)


def test_sampler_greedy_and_top_p():
    logits = jnp.asarray(np.array([0.0, 5.0, 1.0, -2.0], np.float32))
    key = jax.random.PRNGKey(0)
    assert int(sample_top_p(key, logits, temp=0.0)) == 1
    # dominant token holds ~97% mass: top_p=0.5 nucleus is {1} alone
    for i in range(20):
        k = jax.random.PRNGKey(i)
        assert int(sample_top_p(k, logits, top_p=0.5, temp=1.0)) == 1


def test_sampler_top_p_excludes_tail():
    """Tokens outside the nucleus must never be drawn."""
    logits = jnp.asarray(np.array([4.0, 4.0, -10.0, -10.0], np.float32))
    seen = {int(sample_top_p(jax.random.PRNGKey(i), logits,
                             top_p=0.9, temp=1.0)) for i in range(50)}
    assert seen <= {0, 1}
    assert len(seen) == 2          # both nucleus members reachable


def test_byte_tokenizer_round_trip():
    tok = ByteTokenizer()
    text = "Hello, wörld! ☃"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == text
    # streaming pieces concatenate to the same bytes
    pieces = b"".join(tok.token_to_piece(i) for i in ids)
    assert pieces.decode("utf-8") == text
    assert tok.encode("abc", max_len=2) == [tok.bos_id, 3 + ord("a")]


def test_prompt_longer_than_largest_bucket():
    """A prompt between the largest bucket and max_len must still land
    in a program (regression: broadcast crash for bucket < P < max_len)."""
    m = CompletionModel(CFG, buckets=(16,), temp=0.0)
    assert m.buckets[-1] == CFG.max_len
    logits = m.prefill(np.ones(40, np.int32))      # 16 < 40 < 128
    assert logits.shape == (CFG.vocab_size,)
    assert m.pos == 40


def test_byte_tokenizer_out_of_range_ids_are_empty():
    """Lm-head slack rows (vocab wider than the byte table) must stream
    as empty pieces, not crash (regression: ValueError in bytes())."""
    tok = ByteTokenizer()
    assert tok.token_to_piece(300) == b""
    assert tok.token_to_piece(tok.pad_id) == b""
    assert tok.decode([1, 3 + ord("a"), 5000, 2]) == "a"


def test_context_window_guard(model):
    with pytest.raises(ValueError):
        model.prefill(np.ones(CFG.max_len, np.int32))
    with pytest.raises(ValueError):
        model.prefill(np.zeros(0, np.int32))
