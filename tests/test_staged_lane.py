"""StagedLane: device-resident vector-lane cache with O(dirty) re-staging.

Covers VERDICT r1 item 2: a second search after k dirty writes must
transfer O(k) rows, not the whole lane (the round-1 CLI re-uploaded the
full (nslots, dim) matrix per query) — and the r05 dirty-refresh cliff:
large dirty sets chunk through the fixed bucket set (padding waste <=
2x, no fresh jit compiles), instead of padding to one giant scatter."""
from __future__ import annotations

import os
import uuid

import numpy as np
import pytest

from libsplinter_tpu.ops import StagedLane
from libsplinter_tpu.ops.staged_lane import _UPDATE_BUCKETS, _chunk_plan


def _fill(store, n, dim, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    for i in range(n):
        store.set(f"doc/{i}", f"text {i}")
        store.vec_set(f"doc/{i}", vecs[i])
    return vecs


class TestNativePrimitives:
    def test_epochs_snapshot(self, store):
        e0 = store.epochs()
        assert e0.shape == (store.nslots,)
        assert e0.dtype == np.uint64
        store.set("k", b"v")
        e1 = store.epochs()
        idx = store.find_index("k")
        assert e1[idx] > e0[idx]
        assert (np.delete(e1, idx) == np.delete(e0, idx)).all()

    def test_vec_gather(self, store):
        dim = store.vec_dim
        v = np.arange(dim, dtype=np.float32)
        store.set("k", b"v")
        store.vec_set("k", v)
        idx = store.find_index("k")
        empty = next(i for i in range(store.nslots)
                     if store.epoch_at(i) == 0)
        vecs, eps = store.vec_gather(np.array([idx, empty]))
        assert eps[0] == store.epoch_at(idx) and eps[0] % 2 == 0
        np.testing.assert_array_equal(vecs[0], v)
        # a stable never-written slot reports epoch 0 (NOT the torn
        # sentinel) and a zeros row
        assert eps[1] == 0 and eps[1] != store.GATHER_TORN
        assert (vecs[1] == 0).all()

    def test_vec_gather_out_of_range(self, store):
        vecs, eps = store.vec_gather(np.array([store.nslots + 5]))
        assert eps[0] == store.GATHER_TORN
        assert (vecs[0] == 0).all()


class TestStagedLane:
    def test_full_upload_then_incremental(self, store):
        dim = store.vec_dim
        vecs = _fill(store, 20, dim)
        lane = StagedLane(store)
        arr = np.asarray(lane.refresh())
        assert lane.full_uploads == 1 and lane.rows_staged == 0
        for i in range(20):
            np.testing.assert_array_equal(
                arr[store.find_index(f"doc/{i}")], vecs[i])

        # no writes -> zero transfer
        lane.refresh()
        assert lane.full_uploads == 1 and lane.rows_staged == 0

        # k dirty writes -> exactly k rows re-staged
        k = 3
        new = np.ones((k, dim), np.float32) * 7.5
        for i in range(k):
            store.vec_set(f"doc/{i}", new[i])
        arr = np.asarray(lane.refresh())
        assert lane.full_uploads == 1
        assert lane.rows_staged == k
        for i in range(k):
            np.testing.assert_array_equal(
                arr[store.find_index(f"doc/{i}")], new[i])
        # untouched rows still correct
        np.testing.assert_array_equal(
            arr[store.find_index("doc/10")], vecs[10])

    def test_text_write_restages_row(self, store):
        _fill(store, 4, store.vec_dim)
        lane = StagedLane(store)
        lane.refresh()
        store.set("doc/2", "new text bumps the epoch")
        np.asarray(lane.refresh())
        assert lane.rows_staged == 1

    def test_unset_zeroes_staged_row(self, store):
        _fill(store, 4, store.vec_dim)
        lane = StagedLane(store)
        idx = store.find_index("doc/1")
        lane.refresh()
        store.unset("doc/1")
        arr = np.asarray(lane.refresh())
        assert (arr[idx] == 0).all()

    def test_large_update_bucket_padding(self, store):
        n = 150  # > first bucket (64), exercises padding with dup rows
        vecs = _fill(store, n, store.vec_dim)
        lane = StagedLane(store)
        lane.refresh()
        for i in range(n):
            store.vec_set(f"doc/{i}", vecs[i] + 1.0)
        arr = np.asarray(lane.refresh())
        assert lane.rows_staged == n
        for i in (0, 77, n - 1):
            np.testing.assert_array_equal(
                arr[store.find_index(f"doc/{i}")], vecs[i] + 1.0)

    def test_topk_reads_cache(self, store):
        dim = store.vec_dim
        _fill(store, 16, dim, seed=3)
        target = np.zeros(dim, np.float32)
        target[0] = 1.0
        store.set("hit", "the needle")
        store.vec_set("hit", target)
        lane = StagedLane(store)
        scores, idxs = lane.topk(target, k=1)
        assert idxs[0] == store.find_index("hit")
        assert scores[0] == pytest.approx(1.0, abs=1e-5)

    def test_invalidate_forces_full_upload(self, store):
        _fill(store, 4, store.vec_dim)
        lane = StagedLane(store)
        lane.refresh()
        lane.invalidate()
        lane.refresh()
        assert lane.full_uploads == 2


class TestNorms:
    def test_norms_track_incremental_updates(self, store):
        """Row norms are lane-static data maintained at stage time
        (full pass on upload, O(dirty) on refresh) — they must match a
        fresh host computation after incremental writes."""
        dim = store.vec_dim
        _fill(store, 12, dim)
        lane = StagedLane(store)
        lane.refresh()
        want = np.linalg.norm(np.array(store.vectors), axis=1)
        np.testing.assert_allclose(np.asarray(lane.norms), want,
                                   rtol=1e-6)
        store.vec_set("doc/4", np.full(dim, 3.0, np.float32))
        lane.refresh()
        assert lane.full_uploads == 1          # incremental, not re-upload
        want = np.linalg.norm(np.array(store.vectors), axis=1)
        np.testing.assert_allclose(np.asarray(lane.norms), want,
                                   rtol=1e-6)

    def test_topk_uses_staged_norms(self, store):
        dim = store.vec_dim
        _fill(store, 8, dim)
        lane = StagedLane(store)
        slot = store.find_index("doc/3")
        q = np.array(store.vectors)[slot]
        s, i = lane.topk(q, k=1)
        assert int(i[0]) == slot
        assert s[0] == pytest.approx(1.0, abs=1e-5)


class TestChunkPlan:
    """The refresh chunking policy is pure math — pin it exactly."""

    def test_headline_decompositions(self):
        assert _chunk_plan(128) == [64, 64]
        assert _chunk_plan(8192) == [4096, 4096]
        assert _chunk_plan(40000) == [32768, 4096, 4096]

    def test_small_counts_take_one_bucket(self):
        assert _chunk_plan(1) == [64]
        assert _chunk_plan(64) == [64]
        assert _chunk_plan(500) == [512]

    @pytest.mark.parametrize("n", [1, 63, 64, 65, 100, 128, 511, 513,
                                   4095, 4097, 8192, 32768, 32769,
                                   40000, 100000])
    def test_invariants(self, n):
        plan = _chunk_plan(n)
        # every chunk is a precompiled bucket shape
        assert all(b in _UPDATE_BUCKETS for b in plan)
        total = sum(plan)
        assert total >= n                     # covers every dirty row
        # padding waste bounded at 2x (floor of one smallest bucket)
        assert total <= max(2 * n, _UPDATE_BUCKETS[0])


class TestLargeDirtyRefresh:
    """The r05 cliff regression guard: refresh cost must be
    piecewise-linear in the dirty count (chunk count x bucket size),
    with full_uploads pinned at 1 and zero jit compiles beyond the
    fixed bucket set."""

    DIM = 8

    def _big_store(self, k):
        from libsplinter_tpu import Store

        nslots = 1
        while nslots < k * 2:
            nslots *= 2
        nslots = max(nslots, 256)
        name = f"/spt-biglane-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        return Store.create(name, nslots=nslots, max_val=64,
                            vec_dim=self.DIM), name

    @pytest.mark.parametrize("k", [128, 8192, 40000])
    def test_accounting_and_correctness(self, k):
        from libsplinter_tpu import Store
        from libsplinter_tpu.ops.similarity import _scatter_rows_norms_fn

        st, name = self._big_store(k)
        try:
            rng = np.random.default_rng(7)
            v0 = rng.normal(size=(k, self.DIM)).astype(np.float32)
            for i in range(k):
                st.set(f"d/{i}", "x")
            idxs = np.array([st.find_index(f"d/{i}") for i in range(k)])
            for i in range(k):
                st.vec_set_at(int(idxs[i]), v0[i])

            lane = StagedLane(st)
            lane.refresh()
            assert lane.full_uploads == 1 and lane.rows_staged == 0

            fn = _scatter_rows_norms_fn()
            compiles_before = (fn._cache_size()
                               if hasattr(fn, "_cache_size") else None)

            # dirty every row, refresh, and audit the chunk accounting
            v1 = v0 + 1.0
            for i in range(k):
                st.vec_set(f"d/{i}", v1[i])
            arr = np.asarray(lane.refresh())

            assert lane.full_uploads == 1          # never a re-upload
            assert lane.rows_staged == k           # every real row moved
            plan = _chunk_plan(k)
            assert lane.scatter_chunks == len(plan)
            assert lane.rows_padded == sum(plan)
            # piecewise-linear: chunk count x bucket size never pads
            # past 2x the dirty count (the old single-scatter path
            # padded 8,192 -> 32,768: the 53x wall-time cliff)
            assert lane.rows_padded <= max(2 * k, 64)
            assert all(b in _UPDATE_BUCKETS
                       for b in lane.chunk_hist)

            # value correctness on a sample (full compare at small k)
            sample = (np.arange(k) if k <= 1024
                      else rng.choice(k, size=512, replace=False))
            for i in sample:
                np.testing.assert_array_equal(arr[idxs[i]], v1[i])
            # norms maintained O(dirty), exact
            want = np.linalg.norm(v1[sample], axis=1)
            got = np.asarray(lane.norms)[idxs[sample]]
            np.testing.assert_allclose(got, want, rtol=1e-6)

            # no fresh compile beyond the fixed bucket set: a second
            # same-size refresh reuses every program (compile-count
            # hook = the jitted scatter's signature cache)
            if compiles_before is not None:
                # the big refresh compiled exactly one program per
                # DISTINCT bucket in its plan (the jit cache is global
                # across stores/dtypes, so assert the delta) ...
                delta = fn._cache_size() - compiles_before
                assert delta <= len(set(plan))
                # ... and a same-size re-refresh compiles NOTHING: no
                # dirty count ever costs a fresh program at steady state
                steady = fn._cache_size()
                for i in range(k):
                    st.vec_set(f"d/{i}", v0[i])
                lane.refresh()
                assert fn._cache_size() == steady
                assert lane.rows_staged == 2 * k
        finally:
            st.close()
            Store.unlink(name)


class TestWireDtype:
    """f16 staging wire: half the staged bytes, device lane still f32,
    quantization bounded and ranking-preserved; norms stay exact (they
    come from the f32 data, not the wire copy)."""

    def test_f16_upload_and_refresh(self, store):
        dim = store.vec_dim
        vecs = _fill(store, 20, dim)
        lane = StagedLane(store, wire="f16")
        arr = np.asarray(lane.refresh())
        assert arr.dtype == np.float32        # device lane stays f32
        for i in range(20):
            np.testing.assert_allclose(
                arr[store.find_index(f"doc/{i}")], vecs[i],
                atol=2e-3, rtol=2e-3)         # f16 quantization bound
        # incremental path quantizes the same way
        new = np.full(dim, 0.123456, np.float32)
        store.vec_set("doc/0", new)
        arr = np.asarray(lane.refresh())
        assert lane.full_uploads == 1 and lane.rows_staged == 1
        np.testing.assert_allclose(
            arr[store.find_index("doc/0")], new, atol=2e-3, rtol=2e-3)
        # norms are computed from the exact f32 gather, not the wire
        want = np.linalg.norm(np.array(store.vectors), axis=1)
        np.testing.assert_allclose(np.asarray(lane.norms), want,
                                   rtol=1e-6)

    def test_f16_ranking_matches_f32(self, store):
        dim = store.vec_dim
        _fill(store, 32, dim, seed=5)
        f32 = StagedLane(store)
        f16 = StagedLane(store, wire="f16")
        q = np.array(store.vectors)[store.find_index("doc/7")]
        _, i32 = f32.topk(q, k=5)
        _, i16 = f16.topk(q, k=5)
        assert int(i16[0]) == int(i32[0]) == store.find_index("doc/7")
        assert set(map(int, i16)) == set(map(int, i32))

    def test_wire_rejects_unknown(self, store):
        with pytest.raises(ValueError):
            StagedLane(store, wire="int8")

    def test_wire_env_default(self, store, monkeypatch):
        monkeypatch.setenv("SPTPU_LANE_WIRE", "f16")
        lane = StagedLane(store)
        assert lane.wire == "f16"
        monkeypatch.delenv("SPTPU_LANE_WIRE")
        assert StagedLane(store).wire == "f32"
