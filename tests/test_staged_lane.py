"""StagedLane: device-resident vector-lane cache with O(dirty) re-staging.

Covers VERDICT r1 item 2: a second search after k dirty writes must
transfer O(k) rows, not the whole lane (the round-1 CLI re-uploaded the
full (nslots, dim) matrix per query)."""
from __future__ import annotations

import numpy as np
import pytest

from libsplinter_tpu.ops import StagedLane


def _fill(store, n, dim, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    for i in range(n):
        store.set(f"doc/{i}", f"text {i}")
        store.vec_set(f"doc/{i}", vecs[i])
    return vecs


class TestNativePrimitives:
    def test_epochs_snapshot(self, store):
        e0 = store.epochs()
        assert e0.shape == (store.nslots,)
        assert e0.dtype == np.uint64
        store.set("k", b"v")
        e1 = store.epochs()
        idx = store.find_index("k")
        assert e1[idx] > e0[idx]
        assert (np.delete(e1, idx) == np.delete(e0, idx)).all()

    def test_vec_gather(self, store):
        dim = store.vec_dim
        v = np.arange(dim, dtype=np.float32)
        store.set("k", b"v")
        store.vec_set("k", v)
        idx = store.find_index("k")
        empty = next(i for i in range(store.nslots)
                     if store.epoch_at(i) == 0)
        vecs, eps = store.vec_gather(np.array([idx, empty]))
        assert eps[0] == store.epoch_at(idx) and eps[0] % 2 == 0
        np.testing.assert_array_equal(vecs[0], v)
        # a stable never-written slot reports epoch 0 (NOT the torn
        # sentinel) and a zeros row
        assert eps[1] == 0 and eps[1] != store.GATHER_TORN
        assert (vecs[1] == 0).all()

    def test_vec_gather_out_of_range(self, store):
        vecs, eps = store.vec_gather(np.array([store.nslots + 5]))
        assert eps[0] == store.GATHER_TORN
        assert (vecs[0] == 0).all()


class TestStagedLane:
    def test_full_upload_then_incremental(self, store):
        dim = store.vec_dim
        vecs = _fill(store, 20, dim)
        lane = StagedLane(store)
        arr = np.asarray(lane.refresh())
        assert lane.full_uploads == 1 and lane.rows_staged == 0
        for i in range(20):
            np.testing.assert_array_equal(
                arr[store.find_index(f"doc/{i}")], vecs[i])

        # no writes -> zero transfer
        lane.refresh()
        assert lane.full_uploads == 1 and lane.rows_staged == 0

        # k dirty writes -> exactly k rows re-staged
        k = 3
        new = np.ones((k, dim), np.float32) * 7.5
        for i in range(k):
            store.vec_set(f"doc/{i}", new[i])
        arr = np.asarray(lane.refresh())
        assert lane.full_uploads == 1
        assert lane.rows_staged == k
        for i in range(k):
            np.testing.assert_array_equal(
                arr[store.find_index(f"doc/{i}")], new[i])
        # untouched rows still correct
        np.testing.assert_array_equal(
            arr[store.find_index("doc/10")], vecs[10])

    def test_text_write_restages_row(self, store):
        _fill(store, 4, store.vec_dim)
        lane = StagedLane(store)
        lane.refresh()
        store.set("doc/2", "new text bumps the epoch")
        np.asarray(lane.refresh())
        assert lane.rows_staged == 1

    def test_unset_zeroes_staged_row(self, store):
        _fill(store, 4, store.vec_dim)
        lane = StagedLane(store)
        idx = store.find_index("doc/1")
        lane.refresh()
        store.unset("doc/1")
        arr = np.asarray(lane.refresh())
        assert (arr[idx] == 0).all()

    def test_large_update_bucket_padding(self, store):
        n = 150  # > first bucket (64), exercises padding with dup rows
        vecs = _fill(store, n, store.vec_dim)
        lane = StagedLane(store)
        lane.refresh()
        for i in range(n):
            store.vec_set(f"doc/{i}", vecs[i] + 1.0)
        arr = np.asarray(lane.refresh())
        assert lane.rows_staged == n
        for i in (0, 77, n - 1):
            np.testing.assert_array_equal(
                arr[store.find_index(f"doc/{i}")], vecs[i] + 1.0)

    def test_topk_reads_cache(self, store):
        dim = store.vec_dim
        _fill(store, 16, dim, seed=3)
        target = np.zeros(dim, np.float32)
        target[0] = 1.0
        store.set("hit", "the needle")
        store.vec_set("hit", target)
        lane = StagedLane(store)
        scores, idxs = lane.topk(target, k=1)
        assert idxs[0] == store.find_index("hit")
        assert scores[0] == pytest.approx(1.0, abs=1e-5)

    def test_invalidate_forces_full_upload(self, store):
        _fill(store, 4, store.vec_dim)
        lane = StagedLane(store)
        lane.refresh()
        lane.invalidate()
        lane.refresh()
        assert lane.full_uploads == 2


class TestNorms:
    def test_norms_track_incremental_updates(self, store):
        """Row norms are lane-static data maintained at stage time
        (full pass on upload, O(dirty) on refresh) — they must match a
        fresh host computation after incremental writes."""
        dim = store.vec_dim
        _fill(store, 12, dim)
        lane = StagedLane(store)
        lane.refresh()
        want = np.linalg.norm(np.array(store.vectors), axis=1)
        np.testing.assert_allclose(np.asarray(lane.norms), want,
                                   rtol=1e-6)
        store.vec_set("doc/4", np.full(dim, 3.0, np.float32))
        lane.refresh()
        assert lane.full_uploads == 1          # incremental, not re-upload
        want = np.linalg.norm(np.array(store.vectors), axis=1)
        np.testing.assert_allclose(np.asarray(lane.norms), want,
                                   rtol=1e-6)

    def test_topk_uses_staged_norms(self, store):
        dim = store.vec_dim
        _fill(store, 8, dim)
        lane = StagedLane(store)
        slot = store.find_index("doc/3")
        q = np.array(store.vectors)[slot]
        s, i = lane.topk(q, k=1)
        assert int(i[0]) == slot
        assert s[0] == pytest.approx(1.0, abs=1e-5)


class TestWireDtype:
    """f16 staging wire: half the staged bytes, device lane still f32,
    quantization bounded and ranking-preserved; norms stay exact (they
    come from the f32 data, not the wire copy)."""

    def test_f16_upload_and_refresh(self, store):
        dim = store.vec_dim
        vecs = _fill(store, 20, dim)
        lane = StagedLane(store, wire="f16")
        arr = np.asarray(lane.refresh())
        assert arr.dtype == np.float32        # device lane stays f32
        for i in range(20):
            np.testing.assert_allclose(
                arr[store.find_index(f"doc/{i}")], vecs[i],
                atol=2e-3, rtol=2e-3)         # f16 quantization bound
        # incremental path quantizes the same way
        new = np.full(dim, 0.123456, np.float32)
        store.vec_set("doc/0", new)
        arr = np.asarray(lane.refresh())
        assert lane.full_uploads == 1 and lane.rows_staged == 1
        np.testing.assert_allclose(
            arr[store.find_index("doc/0")], new, atol=2e-3, rtol=2e-3)
        # norms are computed from the exact f32 gather, not the wire
        want = np.linalg.norm(np.array(store.vectors), axis=1)
        np.testing.assert_allclose(np.asarray(lane.norms), want,
                                   rtol=1e-6)

    def test_f16_ranking_matches_f32(self, store):
        dim = store.vec_dim
        _fill(store, 32, dim, seed=5)
        f32 = StagedLane(store)
        f16 = StagedLane(store, wire="f16")
        q = np.array(store.vectors)[store.find_index("doc/7")]
        _, i32 = f32.topk(q, k=5)
        _, i16 = f16.topk(q, k=5)
        assert int(i16[0]) == int(i32[0]) == store.find_index("doc/7")
        assert set(map(int, i16)) == set(map(int, i32))

    def test_wire_rejects_unknown(self, store):
        with pytest.raises(ValueError):
            StagedLane(store, wire="int8")

    def test_wire_env_default(self, store, monkeypatch):
        monkeypatch.setenv("SPTPU_LANE_WIRE", "f16")
        lane = StagedLane(store)
        assert lane.wire == "f16"
        monkeypatch.delenv("SPTPU_LANE_WIRE")
        assert StagedLane(store).wire == "f32"
