"""Subprocess driver for the crash-at-every-stage matrix
(test_crash_recovery.py): opens an existing store and runs one daemon
drain with whatever SPTPU_FAULT the parent armed in the environment.
A `crash` fault kills this process mid-drain (exit 137); the parent
then asserts the restarted daemon + client helpers converge.

Usage: python tests/chaos_child.py {searcher|embedder|completer} STORE
"""
from __future__ import annotations

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# invoked by script path: the repo root is not on sys.path by default
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    role, store_name = sys.argv[1], sys.argv[2]
    import numpy as np

    from libsplinter_tpu import Store

    st = Store.open(store_name)
    if role == "searcher":
        from libsplinter_tpu.engine.searcher import Searcher
        sr = Searcher(st)
        sr.attach()
        n = sr.run_once()
        sr.sweep_results()
        print(f"served={n}", flush=True)
    elif role == "embedder":
        from libsplinter_tpu.engine.embedder import Embedder
        emb = Embedder(st, encoder_fn=lambda ts: np.full(
            (len(ts), st.vec_dim), 0.5, np.float32), max_ctx=64)
        emb.attach()
        n = emb.run_once()
        print(f"embedded={n}", flush=True)
    elif role == "embedder_ring":
        # the MODEL path at a tiny geometry with the resident ring
        # engaged (>= 2 full batches per drain): the resident.ring_*
        # fault sites are only reachable through a real ring dispatch
        from libsplinter_tpu.engine.embedder import Embedder
        from libsplinter_tpu.models import default_tokenizer
        from libsplinter_tpu.models.encoder import (EmbeddingModel,
                                                    EncoderConfig)
        cfg = EncoderConfig.tiny(out_dim=st.vec_dim)
        emb = Embedder(st, model=EmbeddingModel(cfg, buckets=(16, 32)),
                       tokenizer=default_tokenizer(cfg.vocab_size),
                       max_ctx=128, batch_cap=4, ring_depth=4)
        emb.attach()
        n = emb.run_once()
        print(f"embedded={n}", flush=True)
    elif role == "completer":
        from libsplinter_tpu.engine.completer import Completer
        comp = Completer(st, generate_fn=lambda p: iter([b"pong "]),
                         template="none")
        comp.attach()
        n = comp.run_once()
        print(f"completions={n}", flush=True)
    elif role == "completer_quant":
        # the int8-quantized continuous lane at tiny geometry: the
        # completer.kv_quant_commit fault site fires right before the
        # quantized commit scatter, so a crash here dies with a
        # claimed (SERVICING) request and half-written pool state —
        # the drill proves the restarted lane reclaims the request
        # and serves from a clean pool (no poisoned pages: the pool
        # dies with the process)
        import jax.numpy as jnp

        from libsplinter_tpu.engine.completer import Completer
        from libsplinter_tpu.models.decoder import (CompletionModel,
                                                    DecoderConfig)

        cfg = DecoderConfig.tiny(dtype=jnp.float32)
        model = CompletionModel(cfg, buckets=(16,), temp=0.0, seed=1)
        # prefix cache OFF: this drill's clean-pool assertion reads
        # pages_used == 0 on the LIVE lane, and warm-cache retention
        # would legitimately hold prompt pages (the prefix+crash
        # composition has its own drill, completer_prefix below)
        comp = Completer(st, model=model, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16, kv_dtype="int8",
                         prefix_cache=False)
        comp.attach()
        comp.run_continuous(
            idle_timeout_ms=20,
            stop_after=float(os.environ.get("SPTPU_CHAOS_RUN_S", "8")))
        print(f"completions={comp.stats.completions}", flush=True)
    elif role == "completer_prefix":
        # the prefix-sharing continuous lane at tiny geometry: the
        # completer.prefix_map fault site fires on a prefix-cache HIT
        # right before map_shared bumps any refcount, so a crash here
        # dies with a claimed request mid table-mapping — pool,
        # refcounts, and radix tree all die with the process, and the
        # drill proves the restarted lane rebuilds a clean pool
        # (zero stranded refcounts) and re-serves the reclaimed
        # request from a cold tree
        import jax.numpy as jnp

        from libsplinter_tpu.engine.completer import Completer
        from libsplinter_tpu.models.decoder import (CompletionModel,
                                                    DecoderConfig)

        cfg = DecoderConfig.tiny(dtype=jnp.float32)
        model = CompletionModel(cfg, buckets=(32,), temp=0.0, seed=1,
                                suffix_buckets=(8,))
        comp = Completer(st, model=model, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=8)
        comp.attach()
        comp.run_continuous(
            idle_timeout_ms=20,
            stop_after=float(os.environ.get("SPTPU_CHAOS_RUN_S", "8")))
        print(f"completions={comp.stats.completions}", flush=True)
    elif role == "tier_completer":
        # the tiered-KV continuous lane at tiny geometry with the
        # host-DRAM spill tier + persistent warm layer armed: the
        # tier.spill site fires on each frozen page's write-through
        # shadow copy, tier.readmit on each DRAM-hit page's
        # device_put return, and tier.restore inside the warm-attach
        # snapshot adoption — crash drills in all three prove a death
        # mid-spill leaves the HBM copy authoritative, a death
        # mid-readmit leaves the shadow intact, and a death
        # mid-restore falls back cold, all with zero admitted loss
        # (test_kv_tier.py runs this role under `spt supervise`)
        import jax.numpy as jnp

        from libsplinter_tpu.engine.completer import Completer
        from libsplinter_tpu.models.decoder import (CompletionModel,
                                                    DecoderConfig)

        cfg = DecoderConfig.tiny(dtype=jnp.float32)
        model = CompletionModel(cfg, buckets=(32,), temp=0.0, seed=1,
                                suffix_buckets=(8,))
        comp = Completer(st, model=model, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=8, kv_tier_pages=32,
                         kv_tier_persist=f"{store_name}-kvtier")
        comp.attach()
        comp.run_continuous(
            idle_timeout_ms=20,
            stop_after=float(os.environ.get("SPTPU_CHAOS_RUN_S", "8")))
        print(f"completions={comp.stats.completions}", flush=True)
    elif role == "completer_sharded":
        # the pod-sharded continuous lane at tiny geometry over a
        # virtual 8-device CPU mesh: the completer.sharded_dispatch
        # fault site is only reachable through a real sharded paged
        # dispatch, and `spt supervise` drives this role as a
        # restartable lane child (test_crash_recovery)
        import re

        os.environ["XLA_FLAGS"] = (re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            os.environ.get("XLA_FLAGS", ""))
            + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except (AttributeError, RuntimeError):
            pass
        import jax.numpy as jnp

        from libsplinter_tpu.engine.completer import Completer
        from libsplinter_tpu.models.decoder import DecoderConfig
        from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                              make_mesh)

        cfg = DecoderConfig.tiny(dtype=jnp.float32)
        model = ShardedCompletionModel(cfg, make_mesh(dp=4, tp=2),
                                       buckets=(16,), temp=0.0, seed=1)
        comp = Completer(st, model=model, max_new_tokens=8,
                         flush_tokens=4, template="none", batch_cap=2,
                         page_size=16)
        comp.attach()
        comp.run_continuous(
            idle_timeout_ms=20,
            stop_after=float(os.environ.get("SPTPU_CHAOS_RUN_S", "8")))
        print(f"completions={comp.stats.completions}", flush=True)
    elif role in ("prefill_lane", "decode_lane"):
        # the disaggregated completer phases at tiny geometry: the
        # prefill.handoff fault site fires after the wire pages are
        # written but before the handoff record (a crash strands a
        # half-written handoff for the reclaim sweep); decode.adopt
        # fires after a DECODE_READY row is claimed but before its
        # pages are imported (a crash rolls the row back to bare
        # DECODE_READY for re-adoption).  test_disagg.py runs both
        # lanes and asserts zero admitted loss either way.
        import jax.numpy as jnp

        from libsplinter_tpu.engine.disagg import (DecodeLane,
                                                   PrefillLane)
        from libsplinter_tpu.models.decoder import (CompletionModel,
                                                    DecoderConfig)

        cfg = DecoderConfig.tiny(dtype=jnp.float32)
        model = CompletionModel(cfg, buckets=(32,), temp=0.0, seed=1,
                                suffix_buckets=(8,))
        cls = PrefillLane if role == "prefill_lane" else DecodeLane
        comp = cls(st, model=model, max_new_tokens=8,
                   flush_tokens=4, template="none", batch_cap=4,
                   page_size=8)
        comp.attach()
        comp.run_continuous(
            idle_timeout_ms=20,
            stop_after=float(os.environ.get("SPTPU_CHAOS_RUN_S", "8")))
        print(f"completions={comp.stats.completions}", flush=True)
    elif role == "pipeliner":
        # the pipeline lane (jax-free): runs the script pump for a
        # bounded window so the pipeliner.exec / pipeliner.verb fault
        # sites fire mid-chain — a `crash` dies with admitted scripts
        # stranded (LBL_SCRIPT_REQ still up), and the parent asserts
        # the restarted lane reclaims and re-runs them
        from libsplinter_tpu.engine.pipeliner import Pipeliner
        pl = Pipeliner(st)
        pl.attach()
        pl.run(idle_timeout_ms=20,
               stop_after=float(os.environ.get("SPTPU_CHAOS_RUN_S",
                                               "8")))
        print(f"scripts={pl.stats.scripts_completed}", flush=True)
    else:
        raise SystemExit(f"unknown role {role!r}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
