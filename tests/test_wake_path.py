"""Embedder wake-path scaling: hot drains are dirty-mask + pending-set
driven, never an O(nslots) label sweep (VERDICT r1 item 6)."""
from __future__ import annotations

import time

import numpy as np
import pytest

from libsplinter_tpu import Store, T_VARTEXT
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.embedder import Embedder


def fake_encoder(dim):
    def enc(texts):
        out = np.zeros((len(texts), dim), np.float32)
        for i, t in enumerate(texts):
            out[i, 0] = 1.0 + len(t)
        return out
    return enc


def make_embedder(store):
    emb = Embedder(store, encoder_fn=fake_encoder(store.vec_dim))
    emb.attach()
    return emb


def _request(store, key, text="some text"):
    store.set(key, text)
    store.set_type(key, T_VARTEXT)
    store.label_or(key, P.LBL_EMBED_REQ)
    store.bump(key)


def test_hot_drain_never_scans_labels(store, monkeypatch):
    emb = make_embedder(store)
    emb.drain(sweep=True)  # settle cold-start state

    def boom(mask):
        raise AssertionError("hot drain must not enumerate labels")

    monkeypatch.setattr(store, "enumerate_indices", boom)
    _request(store, "a")
    assert emb.drain(sweep=False) == 1          # dirty mask drove it
    assert np.abs(store.vec_get("a")).max() > 0
    assert not store.labels("a") & P.LBL_EMBED_REQ


def test_pending_set_carries_rows_across_drains(store):
    emb = make_embedder(store)
    emb.drain(sweep=True)
    _request(store, "b")
    store.drain_dirty()                          # steal the notification
    # hot drain alone would see nothing...
    idx = store.find_index("b")
    emb._pending.add(idx)                        # ...but pending carries it
    assert emb.drain(sweep=False) == 1
    assert idx not in emb._pending


def test_label_cleared_rows_leave_pending(store):
    emb = make_embedder(store)
    _request(store, "c")
    idx = store.find_index("c")
    store.label_clear("c", P.LBL_EMBED_REQ)      # request withdrawn
    emb._pending.add(idx)
    assert emb.drain(sweep=False) == 0
    assert idx not in emb._pending


def test_cold_start_picks_up_preexisting_requests(store):
    _request(store, "early")                     # labeled BEFORE attach
    emb = make_embedder(store)
    store.drain_dirty()                          # dirty bits long gone
    assert emb.drain(sweep=False) == 1           # pending from attach()
    assert np.abs(store.vec_get("early")).max() > 0


def test_reconciliation_sweep_catches_lost_notifications(store):
    emb = make_embedder(store)
    emb.drain(sweep=True)
    _request(store, "lost")
    store.drain_dirty()                          # notification lost
    assert emb.drain(sweep=False) == 0           # hot path can't see it
    assert emb.drain(sweep=True) == 1            # sweep reconciles


@pytest.mark.slow
def test_idle_wake_cost_independent_of_nslots():
    """Idle hot-drain cost must not scale with store size.  The old
    behavior (label sweep per wake) was O(nslots) and fails the ratio
    bound below by ~100x."""
    def idle_cost(nslots):
        name = f"/spt-wake-{nslots}"
        Store.unlink(name)
        st = Store.create(name, nslots=nslots, max_val=64, vec_dim=8)
        emb = Embedder(st, encoder_fn=fake_encoder(8))
        emb.attach()
        emb.drain(sweep=True)
        n_iter = 200
        t0 = time.perf_counter()
        for _ in range(n_iter):
            emb.drain(sweep=False)
        dt = (time.perf_counter() - t0) / n_iter
        st.close()
        Store.unlink(name)
        return dt

    small = idle_cost(1024)
    big = idle_cost(128 * 1024)                  # 128x the slots
    assert big < small * 20 + 1e-3, (
        f"idle drain scaled with nslots: {small*1e6:.0f}us -> "
        f"{big*1e6:.0f}us")
