"""Behavioral spec of the core store — parity with the reference TAP suite
(splinter_test.c:85-533, SURVEY.md §4): CRUD, size query, list, mop modes,
snapshots, named types + BIGUINT promotion, timestamps, embedding
round-trip, integer ops (carry/borrow, EPROTOTYPE), tandem keys, purge,
system keys, append, persistence."""
import os
import uuid

import numpy as np
import pytest

import libsplinter_tpu as sp
from libsplinter_tpu import Eagain, Store


def test_create_open_close(tmp_path):
    name = f"/spt-lc-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    st = Store.create(name, nslots=32, max_val=128, vec_dim=0)
    st.set("a", b"1")
    st2 = Store.open(name)
    assert st2.get("a") == b"1"
    st2.close()
    st.close()
    Store.unlink(name)


def test_create_is_exclusive(tmp_path):
    """Re-creating a live store must fail (it would corrupt peers);
    overwrite=True unlinks first."""
    name = f"/spt-excl-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    st = Store.create(name, nslots=32, max_val=128, vec_dim=0)
    with pytest.raises(OSError):
        Store.create(name, nslots=32, max_val=128, vec_dim=0)
    st.close()
    st2 = Store.create(name, nslots=32, max_val=128, vec_dim=0,
                       overwrite=True)
    st2.close()
    Store.unlink(name)


def test_open_missing_fails():
    with pytest.raises(OSError):
        Store.open(f"/spt-missing-{uuid.uuid4().hex}")


def test_persistent_file_backed(tmp_path):
    path = str(tmp_path / "store.spt")
    st = Store.create(path, nslots=32, max_val=128, vec_dim=8,
                      persistent=True)
    st.set("persist", b"across-restart")
    st.vec_set("persist", np.arange(8, dtype=np.float32))
    st.close()
    # the store IS the checkpoint: a fresh open sees everything
    st2 = Store.open(path, persistent=True)
    assert st2.get("persist") == b"across-restart"
    assert st2.vec_get("persist")[7] == 7.0
    st2.close()
    Store.unlink(path, persistent=True)


def test_set_get_roundtrip(store):
    store.set("k", b"hello world")
    assert store.get("k") == b"hello world"
    store.set("k", b"overwrite")
    assert store.get("k") == b"overwrite"


def test_get_missing_raises(store):
    with pytest.raises(KeyError):
        store.get("nope")


def test_size_query(store):
    store.set("k", b"12345")
    assert store.value_len("k") == 5


def test_value_too_large(store):
    with pytest.raises(OSError):
        store.set("big", b"x" * (store.max_val + 1))


def test_key_too_long(store):
    with pytest.raises(OSError):
        store.set("k" * 200, b"x")


def test_unset(store):
    store.set("gone", b"x")
    store.unset("gone")
    with pytest.raises(KeyError):
        store.get("gone")
    with pytest.raises(KeyError):
        store.unset("gone")


def test_unset_then_reuse_slot(store):
    """Tombstones keep probe chains intact and get reused."""
    for i in range(50):
        store.set(f"k{i}", b"v")
    for i in range(0, 50, 2):
        store.unset(f"k{i}")
    for i in range(0, 50, 2):  # re-insert over tombstones
        store.set(f"k{i}", b"w")
    for i in range(50):
        assert store.get(f"k{i}") in (b"v", b"w")


def test_list(store):
    keys = {f"key-{i}" for i in range(10)}
    for k in keys:
        store.set(k, b"x")
    assert set(store.list()) >= keys
    assert set(iter(store)) >= keys


def test_contains(store):
    store.set("here", b"x")
    assert "here" in store
    assert "not-here" not in store


def test_append(store):
    store.set("log", b"hello")
    store.append("log", b" world")
    assert store.get("log") == b"hello world"


def test_append_to_missing_creates(store):
    store.append("fresh", b"start")
    assert store.get("fresh") == b"start"


def test_append_overflow(store):
    store.set("full", b"x" * (store.max_val - 4))
    with pytest.raises(OSError):
        store.append("full", b"12345678")


def test_epoch_advances_by_two_per_write(store):
    store.set("e", b"1")
    e1 = store.epoch("e")
    assert e1 % 2 == 0 and e1 >= 2
    store.set("e", b"2")
    assert store.epoch("e") == e1 + 2


def test_global_epoch_advances(store):
    g0 = store.header().global_epoch
    store.set("a", b"x")
    store.set("b", b"y")
    assert store.header().global_epoch >= g0 + 2


def test_header_snapshot(store):
    h = store.header()
    assert h.magic == 0x53505455
    assert h.version == 1
    assert h.nslots == 256
    assert h.vec_dim == 32
    assert h.mop_mode == sp.MOP_HYBRID  # default for new stores
    store.set("one", b"x")
    assert store.header().used_slots == 1


def test_slot_snapshot(store):
    store.set("snap", b"abcd")
    store.label_or("snap", 0x5)
    s = store.slot("snap")
    assert s.key == "snap"
    assert s.val_len == 4
    assert s.labels == 0x5
    assert s.epoch % 2 == 0
    assert s.ctime > 0 and s.atime > 0
    assert store.slot_at(s.index).key == "snap"


def test_named_types(store):
    store.set("t", b"x")
    assert store.get_type("t") == sp.T_VOID
    store.set_type("t", sp.T_JSON)
    assert store.get_type("t") == sp.T_JSON
    store.set_type("t", sp.T_VARTEXT)
    assert store.get_type("t") == sp.T_VARTEXT


def test_biguint_promotion(store):
    store.set("n", b"12345")
    store.set_type("n", sp.T_BIGUINT)
    assert store.get_type("n") == sp.T_BIGUINT
    assert store.get_uint("n") == 12345
    assert store.value_len("n") == 8


def test_biguint_promotion_rejects_garbage(store):
    store.set("g", b"not-a-number")
    with pytest.raises(OSError):
        store.set_type("g", sp.T_BIGUINT)


def test_integer_ops(store):
    store.set_uint("c", 10)
    assert store.integer_op("c", sp.IOP_INC) == 11
    assert store.integer_op("c", sp.IOP_DEC) == 10
    assert store.integer_op("c", sp.IOP_ADD, 32) == 42
    assert store.integer_op("c", sp.IOP_SUB, 2) == 40
    assert store.integer_op("c", sp.IOP_AND, 0xF) == 8
    assert store.integer_op("c", sp.IOP_OR, 0x30) == 0x38
    assert store.integer_op("c", sp.IOP_XOR, 0xFF) == 0xC7
    assert store.integer_op("c", sp.IOP_NOT) == (~0xC7) & (2**64 - 1)


def test_integer_carry_borrow(store):
    store.set_uint("w", 2**64 - 1)
    assert store.integer_op("w", sp.IOP_INC) == 0  # wraps
    assert store.integer_op("w", sp.IOP_DEC) == 2**64 - 1  # borrows back


def test_integer_op_wrong_type_eprototype(store):
    store.set("s", b"text")
    with pytest.raises(OSError) as exc:
        store.integer_op("s", sp.IOP_INC)
    import errno
    assert exc.value.errno == errno.EPROTOTYPE


def test_tandem_keys(store):
    n = store.tandem_set("doc", [b"chunk0", b"chunk1", b"chunk2"])
    assert n == 3
    assert store.tandem_count("doc") == 3
    assert store.tandem_get("doc", 0) == b"chunk0"
    assert store.tandem_get("doc", 2) == b"chunk2"
    assert store.get("doc.1") == b"chunk1"  # plain keys underneath
    removed = store.tandem_unset("doc", 16)
    assert removed == 3
    assert store.tandem_count("doc") == 0


def test_embedding_roundtrip(store):
    store.set("vec", b"text")
    v = np.random.default_rng(0).normal(size=32).astype(np.float32)
    store.vec_set("vec", v)
    np.testing.assert_array_equal(store.vec_get("vec"), v)


def test_embedding_zeroed_on_unset_and_new_key(store):
    store.set("z", b"a")
    store.vec_set("z", np.ones(32, dtype=np.float32))
    store.unset("z")
    store.set("z", b"b")  # may or may not reuse the slot
    np.testing.assert_array_equal(store.vec_get("z"),
                                  np.zeros(32, dtype=np.float32))


def test_vector_lane_is_zero_copy(store):
    """The SoA lane view reflects vec_set without copies."""
    store.set("lane", b"x")
    idx = store.find_index("lane")
    v = np.full(32, 7.5, dtype=np.float32)
    store.vec_set("lane", v)
    np.testing.assert_array_equal(store.vectors[idx], v)
    assert store.vectors.shape == (256, 32)


def test_vec_on_novec_store(store_novec):
    store_novec.set("k", b"x")
    with pytest.raises(OSError):
        store_novec.vec_set("k", np.zeros(8, dtype=np.float32))


def test_vec_commit_batch_epoch_gate(store):
    store.set("a", b"one")
    store.set("b", b"two")
    ia, ib = store.find_index("a"), store.find_index("b")
    ea, eb = store.epoch_at(ia), store.epoch_at(ib)
    store.set("b", b"changed")  # invalidates eb
    rows = np.array([ia, ib], dtype=np.uint32)
    epochs = np.array([ea, eb], dtype=np.uint64)
    vecs = np.ones((2, 32), dtype=np.float32)
    res = store.vec_commit_batch(rows, epochs, vecs)
    assert res[0] == 0          # committed
    assert res[1] != 0          # -ESTALE: raced
    assert store.vec_get("a")[0] == 1.0
    assert store.vec_get("b")[0] == 0.0


def test_vec_commit_batch_write_once(store):
    store.set("w1", b"x")
    idx = store.find_index("w1")
    store.vec_set("w1", np.full(32, 2.0, dtype=np.float32))
    rows = np.array([idx], dtype=np.uint32)
    epochs = np.array([store.epoch_at(idx)], dtype=np.uint64)
    res = store.vec_commit_batch(rows, epochs,
                                 np.ones((1, 32), dtype=np.float32),
                                 write_once=True)
    assert res[0] != 0  # -EEXIST
    assert store.vec_get("w1")[0] == 2.0


def test_mop_modes(store):
    assert store.get_mop() == sp.MOP_HYBRID
    store.set_mop(sp.MOP_OFF)
    assert store.get_mop() == sp.MOP_OFF
    store.set_mop(sp.MOP_FULL)
    assert store.get_mop() == sp.MOP_FULL
    # full-boil: shrinking a value leaves no stale tail
    store.set("m", b"A" * 512)
    store.set("m", b"B")
    assert store.get("m") == b"B"
    store.set_mop(sp.MOP_HYBRID)


def test_purge_survival(store):
    for i in range(20):
        store.set(f"p{i}", f"value-{i}".encode())
    store.unset("p3")
    swept = store.purge()
    assert swept > 0
    for i in range(20):
        if i == 3:
            continue
        assert store.get(f"p{i}") == f"value-{i}".encode()


def test_system_key(store):
    store.set_system("__scratch")
    s = store.slot("__scratch")
    assert s.val_len == store.max_val
    assert s.flags & sp.native_abi.F_SYSTEM
    assert store.get_type("__scratch") == sp.T_BINARY


def test_user_flags(store):
    store.set("u", b"x")
    store.slot_usr_set("u", 0xA5)
    assert store.slot_usr_get("u") == 0xA5
    store.config_set_user(0xB)
    assert store.config_get_user() == 0xB
    assert store.config_get_user() <= 0xF  # only 4 store-level bits


def test_retrain_backward_epoch(store):
    store.set("r", b"x")
    store.set("r", b"y")
    store.vec_set("r", np.ones(32, dtype=np.float32))
    before = store.epoch("r")
    assert before > 4
    store.retrain("r")
    after = store.epoch("r")
    assert after == 4            # backward epoch = "revalidate me"
    assert after < before
    np.testing.assert_array_equal(store.vec_get("r"),
                                  np.zeros(32, dtype=np.float32))
    assert store.get("r") == b"y"  # value survives retrain


def test_timestamps_backfill(store):
    store.set("t", b"x")
    before = store.slot("t").ctime
    delta = Store.ticks_per_us() * 1000  # 1 ms ago
    store.stamp("t", which=0, ticks_ago=delta)
    after = store.slot("t").ctime
    assert after != before
    assert after < Store.now()


def test_now_monotonic():
    a = Store.now()
    b = Store.now()
    assert b >= a
    assert Store.ticks_per_us() >= 1


def test_poll_timeout(store):
    store.set("pp", b"x")
    assert store.poll("pp", timeout_ms=30) is False


def test_poll_wakes_on_write(store):
    import threading
    store.set("pw", b"x")

    def writer():
        import time
        time.sleep(0.05)
        w = Store.open(store.name)
        w.set("pw", b"y")
        w.close()

    t = threading.Thread(target=writer)
    t.start()
    assert store.poll("pw", timeout_ms=2000) is True
    t.join()


def test_slot_exhaustion(store_novec):
    st = store_novec
    filled = 0
    try:
        for i in range(st.nslots + 8):
            st.set(f"fill-{i}", b"x")
            filled += 1
    except OSError:
        pass
    assert filled == st.nslots


def test_parse_failure_diag(store):
    assert store.header().parse_failures == 0
    store.report_parse_failure()
    h = store.header()
    assert h.parse_failures == 1


def test_open_numa(store):
    """NUMA-bound open maps the store; bind result is advisory
    (reference parity: splinter_open_numa, splinter.c:250-264)."""
    import errno

    store.set("numa-k", b"v")
    st2, bind_rc = type(store).open_numa(store.name, 0)
    try:
        assert bind_rc in (0, -errno.ENOSYS, -errno.EPERM, -errno.EINVAL)
        assert st2.get("numa-k") == b"v"
    finally:
        st2.close()
    st3, bad_rc = type(store).open_numa(store.name, -1)
    st3.close()
    assert bad_rc == -errno.EINVAL
