"""Int8 blockwise weight residency (models/quant.py).

The reference runs quantized GGUF checkpoints through ggml's kernels
(/root/reference/splainference.cpp:414-448); here Q8_0-geometry int8
weights live resident in HBM and dequantize inside the forward.  The
correctness bar: quantize/dequant error bounded by the block scale,
QuantDense == dense-with-dequantized-kernel, and a quantized decoder
that tracks its float source closely enough to serve.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import (CompletionModel, Decoder,
                                            DecoderConfig, init_cache)
from libsplinter_tpu.models.quant import (QBLOCK, QuantDense,
                                          dequantize_kernel,
                                          quantize_decoder_params,
                                          quantize_kernel)


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = rng.normal(0, 0.05, (64, 48)).astype(np.float32)
    qp = quantize_kernel(w)
    w_hat = dequantize_kernel(qp)
    # symmetric Q8_0: per-element roundoff is at most half a step
    step = np.repeat(np.asarray(qp["scale"]), QBLOCK, axis=0)
    assert (np.abs(w - w_hat) <= step / 2 + 1e-7).all()
    # an already-quantized grid is exact
    qp2 = quantize_kernel(w_hat)
    assert np.allclose(dequantize_kernel(qp2), w_hat, atol=1e-7)


def test_quantize_zero_block():
    w = np.zeros((QBLOCK * 2, 8), np.float32)
    w[QBLOCK:] = 0.01
    qp = quantize_kernel(w)
    assert np.isfinite(qp["scale"]).all()
    assert (dequantize_kernel(qp)[:QBLOCK] == 0).all()


def test_quant_dense_matches_dequantized_matmul():
    rng = np.random.default_rng(1)
    w = rng.normal(0, 0.05, (64, 32)).astype(np.float32)
    x = rng.normal(0, 1, (4, 64)).astype(np.float32)
    qp = quantize_kernel(w)
    mod = QuantDense(32, dtype=jnp.float32)
    y = mod.apply({"params": {"q": jnp.asarray(qp["q"]),
                              "scale": jnp.asarray(qp["scale"])}},
                  jnp.asarray(x))
    ref = x @ dequantize_kernel(qp)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_quant_dense_rejects_unaligned_input():
    mod = QuantDense(8, dtype=jnp.float32)
    with pytest.raises(ValueError, match="not a multiple"):
        mod.init(jax.random.PRNGKey(0), jnp.zeros((1, 33)))


@pytest.fixture(scope="module")
def float_and_quant():
    cfg = DecoderConfig.tiny(dtype=jnp.float32)
    fm = CompletionModel(cfg, buckets=(16,), temp=0.0, seed=3)
    qcfg = DecoderConfig.tiny(dtype=jnp.float32, quantized=True)
    qm = CompletionModel(qcfg, buckets=(16,), temp=0.0,
                         params=fm.params)    # auto-quantized float tree
    return fm, qm


def test_quantized_decoder_tracks_float_source(float_and_quant):
    """Prefill logits of the quantized model must correlate tightly
    with the float source (int8 noise, not divergence)."""
    fm, qm = float_and_quant
    prompt = np.arange(1, 9, dtype=np.int32)
    lf = fm.prefill(prompt)
    fm.reset()
    lq = qm.prefill(prompt)
    qm.reset()
    lf, lq = np.asarray(lf), np.asarray(lq)
    cos = float(np.dot(lf, lq) /
                (np.linalg.norm(lf) * np.linalg.norm(lq) + 1e-9))
    assert cos > 0.99, f"cosine {cos}"


def test_quantized_generation_end_to_end(float_and_quant):
    """The full serving surface runs quantized: serial generate_tokens
    and batched generate_batch, greedy, matching each other."""
    _, qm = float_and_quant
    prompts = [np.arange(1, 6, dtype=np.int32),
               np.array([3, 1, 2], np.int32)]
    serial = []
    for p in prompts:
        serial.append([int(t) for t in qm.generate_tokens(p, 8, chunk=4)])
        qm.reset()
    cols = [c for c in qm.generate_batch(prompts, 8, chunk=4)]
    qm.reset()
    batched = [list(map(int, r)) for r in np.stack(cols, axis=1)]
    assert batched == serial


def test_quantize_tree_idempotent(float_and_quant):
    fm, _ = float_and_quant
    once = quantize_decoder_params(fm.params)
    twice = quantize_decoder_params(once)
    chex_equal = jax.tree.all(jax.tree.map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()),
        once, twice))
    assert chex_equal


def test_quantized_moe_tracks_float_source():
    """MoE expert stacks quantize too: the int8-resident MoE decoder's
    prefill logits track the float source."""
    from libsplinter_tpu.models.moe import (MoeDecoderConfig,
                                            moe_completion_model)

    cfg = MoeDecoderConfig.tiny(dtype=jnp.float32)
    fm = moe_completion_model(cfg, buckets=(16,), temp=0.0, seed=7)
    qcfg = MoeDecoderConfig.tiny(dtype=jnp.float32, quantized=True)
    qm = moe_completion_model(qcfg, buckets=(16,), temp=0.0,
                              params=fm.params)
    prompt = np.arange(1, 9, dtype=np.int32)
    lf = np.asarray(fm.prefill(prompt))
    fm.reset()
    lq = np.asarray(qm.prefill(prompt))
    qm.reset()
    cos = float(np.dot(lf, lq) /
                (np.linalg.norm(lf) * np.linalg.norm(lq) + 1e-9))
    assert cos > 0.99, f"cosine {cos}"
    # the quantized tree really is int8-resident
    leaves = jax.tree.leaves(qm.params)
    assert any(lv.dtype == jnp.int8 for lv in leaves)
    # and serves end to end
    toks = [int(t) for t in qm.generate_tokens(prompt, 6, chunk=3)]
    qm.reset()
    assert len(toks) == 6


def test_quantized_moe_ep_sharded():
    """Int8 expert stacks shard on the ep axis: sharded quantized MoE
    prefill equals unsharded quantized."""
    from libsplinter_tpu.models.moe import (MoeDecoderConfig,
                                            moe_completion_model)
    from libsplinter_tpu.parallel import make_mesh

    cfg = MoeDecoderConfig.tiny(dtype=jnp.float32, quantized=True)
    base = moe_completion_model(cfg, buckets=(16,), temp=0.0, seed=9)
    mesh = make_mesh(dp=2, tp=2, sp=1, ep=2)
    sh = moe_completion_model(cfg, mesh, buckets=(16,), temp=0.0,
                              params=base.params)
    prompt = np.arange(1, 7, dtype=np.int32)
    lu = np.asarray(base.prefill(prompt))
    base.reset()
    ls = np.asarray(sh.prefill(prompt))
    sh.reset()
    np.testing.assert_allclose(lu, ls, rtol=2e-4, atol=2e-4)


def test_quantized_sharded_serving():
    """Int8 trees shard over the tp mesh axis (parallel/serve.py
    pspecs): sharded quantized prefill equals unsharded quantized."""
    from libsplinter_tpu.parallel import ShardedCompletionModel, make_mesh

    cfg = DecoderConfig.tiny(dtype=jnp.float32, quantized=True)
    base = CompletionModel(cfg, buckets=(16,), temp=0.0, seed=5)
    mesh = make_mesh(dp=4, tp=2, sp=1)
    sh = ShardedCompletionModel(cfg, mesh=mesh, buckets=(16,), temp=0.0,
                                params=base.params)
    prompt = np.arange(1, 7, dtype=np.int32)
    lu = base.prefill(prompt)
    base.reset()
    ls = sh.prefill(prompt)
    sh.reset()
    np.testing.assert_allclose(np.asarray(lu), np.asarray(ls),
                               rtol=2e-4, atol=2e-4)
