"""Cross-request prefix sharing (ISSUE 14 / ROADMAP item 2): the
refcounted copy-on-write page pool + host-side radix prefix cache
(engine/prefix_cache.py, models/decoder.PagedKVCache).

Covers: the refcount churn drill (randomized join/finish/evict cycles
leak nothing, double-free nothing, and keep refcount-0 <=> free-list
XOR tree-retention), COW-vs-private byte-exact greedy decode (f32 and
int8, single-chip and tp=2), the >= 4x rows-per-page-budget
multiplier, LRU eviction + tenant quotas, the mid-flight joiner that
maps a prefix another live row is still decoding from, the
bp-memo staleness-eviction regression, heartbeat gauges, the loadgen
shared-prefix knob, and the supervised completer.prefix_map chaos
drill.  `make prefix-check` runs this file + the speedup gate
(scripts/prefix_speedup_check.py).
"""
from __future__ import annotations

import json
import random
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.completer import Completer
from libsplinter_tpu.engine.prefix_cache import PrefixCache
from libsplinter_tpu.models.decoder import CompletionModel, DecoderConfig

PAGE = 8
CFG = DecoderConfig.tiny(dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return CompletionModel(CFG, buckets=(32, 64), temp=0.0, seed=1,
                           suffix_buckets=(8, 16))


def _mkstore(tmp_path, tag, **kw):
    name = f"/spt-{tag}-{tmp_path.name}"
    Store.unlink(name)
    kw.setdefault("nslots", 128)
    kw.setdefault("max_val", 4096)
    kw.setdefault("vec_dim", 8)
    return name, Store.create(name, **kw)


def _attach_pc(cache, **kw):
    pc = PrefixCache(cache.page, **kw)
    pc.attach(cache)
    cache.prefix_cache = pc
    return pc


def _check_invariants(cache, pc):
    """The churn drill's page-accounting invariants."""
    refs = np.zeros(cache.n_blocks, np.int64)
    for owned in cache._owned:
        for bid in owned:
            refs[bid] += 1
    # refcounts == table references, exactly
    assert np.array_equal(refs[1:], cache.refcounts[1:]), \
        (refs.tolist(), cache.refcounts.tolist())
    free = set(cache._free)
    assert len(free) == len(cache._free), "free list duplicate"
    tree = {bid for bid in range(1, cache.n_blocks)
            if pc is not None and pc.retains(bid)}
    assert not free & tree, "page both free and tree-retained"
    for bid in range(1, cache.n_blocks):
        if refs[bid] > 0:
            assert bid not in free, f"page {bid} live AND free"
        else:
            assert bid in free or bid in tree, \
                f"page {bid} leaked (zero-ref, not free, not cached)"
    if pc is not None:
        # the O(1) incremental counter must track a brute recount
        brute = sum(1 for bid in tree if cache.refcounts[bid] == 0)
        assert pc.evictable_count() == brute, \
            (pc.evictable_count(), brute)


# ---------------------------------------------------------------- mechanics

def test_map_shared_refcounts_and_full_cover_cow(model):
    """Full-cover joiner: table write + replay chunk, byte-identical
    to private serving, exactly one COW copy, int8-frozen-scale
    discipline covered by the int8 variant below."""
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache)
    prompt = (np.arange(1, 25, dtype=np.int32) % 200) + 1  # 3 pages
    l0 = model.paged_prefill_row(cache, prompt, 0)
    assert pc.insert(prompt, cache, 0, tenant=1) == 3
    bids, match = pc.lookup(prompt)
    assert match == 24 and len(bids) == 3
    cache.map_shared(1, bids)
    cache.lengths[1] = 23
    assert all(cache.refcounts[b] == 2 for b in bids)
    assert cache.ensure(1, 32)
    toks = np.full((4,), -1, np.int32)
    toks[0] = int(np.argmax(l0))
    toks[1] = int(prompt[-1])          # the replay token
    out = model.paged_decode_chunk(cache, toks, 7)
    donor = [int(toks[0])] + [int(x) for x in out[0][:6]]
    joiner = [int(x) for x in out[1]]
    assert joiner == donor
    assert pc.stats.cow_copies == 1
    # the COW'd tail is private now; the shared original kept its refs
    assert cache.refcounts[bids[-1]] == 1
    cache.free_row(0)
    cache.free_row(1)
    _check_invariants(cache, pc)
    # all three pages retained zero-ref (evictable), none leaked
    assert pc.evictable_count() == 3
    assert cache.available_pages == cache.n_blocks - 1


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_cow_vs_private_byte_exact(model, kv_dtype):
    """COW-vs-private byte-exact greedy decode, f32 and int8 pools.
    For int8 the shared pages are frozen read-only: their per-page
    scales must stay byte-stable across the join + decode (the
    stale-scale hazard is structurally gone)."""
    cache = model.init_paged(4, page=PAGE, kv_dtype=kv_dtype)
    pc = _attach_pc(cache)
    prompt = (np.arange(3, 27, dtype=np.int32) % 150) + 2
    l0 = model.paged_prefill_row(cache, prompt, 0)
    pc.insert(prompt, cache, 0)
    shared_bids = [int(cache.tables[0, j]) for j in range(3)]
    if kv_dtype == "int8":
        ks0 = [np.asarray(s)[shared_bids].copy()
               for s in cache.k_scales]
        vs0 = [np.asarray(s)[shared_bids].copy()
               for s in cache.v_scales]
    bids, match = pc.lookup(prompt)
    assert match == len(prompt)
    cache.map_shared(1, bids)
    cache.lengths[1] = len(prompt) - 1
    cache.ensure(1, 40)
    toks = np.full((4,), -1, np.int32)
    toks[0] = int(np.argmax(l0))
    toks[1] = int(prompt[-1])
    out = model.paged_decode_chunk(cache, toks, 8)
    assert [int(x) for x in out[1]] == \
        [int(toks[0])] + [int(x) for x in out[0][:7]]
    assert pc.stats.cow_copies == 1
    if kv_dtype == "int8":
        for s, before in zip(cache.k_scales, ks0):
            assert np.array_equal(np.asarray(s)[shared_bids], before)
        for s, before in zip(cache.v_scales, vs0):
            assert np.array_equal(np.asarray(s)[shared_bids], before)
    cache.free_row(0)
    cache.free_row(1)
    _check_invariants(cache, pc)


def test_suffix_prefill_matches_private(model):
    """Partial hit: mapped prefix + paged suffix prefill must produce
    the same first token and decode stream as a private full
    prefill — across a suffix long enough to loop the largest
    suffix bucket."""
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache)
    prefix = (np.arange(1, 17, dtype=np.int32) % 90) + 1   # 2 pages
    model.paged_prefill_row(cache, prefix, 0)
    pc.insert(prefix, cache, 0)
    for extra in (3, 21):              # < and > the 16-token bucket
        tail = (np.arange(extra, dtype=np.int32) % 50) + 5
        full = np.concatenate([prefix, tail])
        bids, match = pc.lookup(full)
        assert match == 16
        cache.map_shared(1, bids)
        cache.lengths[1] = match
        assert cache.ensure(1, len(full) + 8)
        lg = model.paged_append_prefill(cache, full[match:], 1)
        ref_cache = model.init_paged(2, page=PAGE)
        lr = model.paged_prefill_row(ref_cache, full, 0)
        t, tr = int(np.argmax(lg)), int(np.argmax(lr))
        assert t == tr
        ta = np.full((4,), -1, np.int32)
        ta[1] = t
        tb = np.full((2,), -1, np.int32)
        tb[0] = tr
        assert [int(x) for x in model.paged_decode_chunk(
            cache, ta, 6)[1]] == \
            [int(x) for x in model.paged_decode_chunk(
                ref_cache, tb, 6)[0]]
        cache.free_row(1)
    cache.free_row(0)
    _check_invariants(cache, pc)


def test_refcount_churn_drill(model):
    """Randomized join/map/finish/evict cycles over a tiny pool:
    zero leaked pages, zero double-frees, refcount-0 <=> free-list
    XOR tree-retention — checked after every step."""
    cache = model.init_paged(6, page=PAGE, pool_pages=48)
    pc = _attach_pc(cache)
    rng = random.Random(7)
    prompts = [((np.arange(1, 1 + n, dtype=np.int32) * m) % 120) + 1
               for n, m in ((16, 3), (24, 5), (16, 7), (32, 11))]
    live: dict[int, int] = {}          # row -> prompt idx
    for step in range(120):
        op = rng.random()
        free_rows = [r for r in range(6) if r not in live]
        if op < 0.5 and free_rows:
            r = free_rows[0]
            pi = rng.randrange(len(prompts))
            ids = prompts[pi]
            bids, match = pc.lookup(ids)
            need = (cache.pages_needed(len(ids) + PAGE)
                    - len(bids) + 1)
            if need > cache.available_pages:
                continue               # backpressure: the honest path
            if match == len(ids):
                cache.map_shared(r, bids)
                pc.commit_hit(ids, match)
                cache.lengths[r] = match - 1
                cache.ensure(r, len(ids) + PAGE)
                # the completer COWs the replay page eagerly at
                # admission (the need check counted it) — mirror that
                model._cow_fixups(cache)
            elif match:
                cache.map_shared(r, bids)
                pc.commit_hit(ids, match)
                cache.lengths[r] = match
                cache.ensure(r, len(ids) + PAGE)
                model.paged_append_prefill(cache, ids[match:], r)
            else:
                pc.note_miss()
                model.paged_prefill_row(cache, ids, r)
                cache.ensure(r, len(ids) + PAGE)
            pc.insert(ids, cache, r, tenant=pi % 3)
            live[r] = pi
        elif op < 0.75 and live:
            # decode only within every live row's reservation (the
            # real lane's admission contract; a row at its budget
            # would otherwise exhaust the pool mid-decode)
            if all(cache.pages_needed(int(cache.lengths[r]) + 2)
                   <= len(cache._owned[r]) for r in live):
                toks = np.full((6,), -1, np.int32)
                for r in live:
                    toks[r] = 9
                model.paged_decode_chunk(cache, toks, 2)
        elif op < 0.92 and live:
            r = rng.choice(list(live))
            cache.free_row(r)
            del live[r]
        else:
            pc.reclaim(rng.randrange(1, 4))
        _check_invariants(cache, pc)
    for r in list(live):
        cache.free_row(r)
    _check_invariants(cache, pc)
    pc.reclaim(cache.n_blocks)
    assert cache.free_pages == cache.n_blocks - 1
    assert pc.shared_pages() == 0


def test_rows_per_envelope_at_least_4x(model):
    """The fixed page budget must seat >= 4x more concurrent rows
    under sharing than under private paging: the admission math
    (worst-case reservation minus hit pages plus the COW page) at
    cache level, the same arithmetic run_continuous uses."""
    prompt_pages, budget = 15, 64
    prompt = (np.arange(1, 1 + prompt_pages * PAGE,
                        dtype=np.int32) % 200) + 1
    worst = cache_pages = prompt_pages + 1     # prompt + 1 growth page

    private = model.init_paged(32, page=PAGE, pool_pages=budget)
    n_private = 0
    for r in range(32):
        if not private.ensure(r, worst * PAGE):
            break
        n_private += 1

    shared = model.init_paged(32, page=PAGE, pool_pages=budget)
    pc = _attach_pc(shared)
    model.paged_prefill_row(shared, prompt, 0)
    shared.ensure(0, worst * PAGE)
    pc.insert(prompt, shared, 0)
    n_shared = 1
    for r in range(1, 32):
        bids, match = pc.lookup(prompt)
        need = shared.pages_needed(worst * PAGE) - len(bids) + 1
        if need > shared.available_pages:
            break
        shared.map_shared(r, bids)
        shared.lengths[r] = match - 1
        shared.ensure(r, worst * PAGE)
        model._cow_fixups(shared)      # the replay page is real cost
        n_shared += 1
    assert cache_pages == worst
    assert n_shared >= 4 * n_private, (n_shared, n_private)


def test_eviction_lru_and_reprefill(model):
    """Zero-ref cached pages evict LRU-first under allocation
    pressure; an evicted prefix simply misses and re-prefills
    correctly (no dangling page ids)."""
    cache = model.init_paged(4, page=PAGE, pool_pages=16)
    pc = _attach_pc(cache)
    a = (np.arange(1, 17, dtype=np.int32) % 80) + 1
    b = ((np.arange(1, 17, dtype=np.int32) * 3) % 80) + 1
    for ids in (a, b):
        model.paged_prefill_row(cache, ids, 0)
        pc.insert(ids, cache, 0)
        cache.free_row(0)
    assert pc.shared_pages() == 4
    _, mb = pc.lookup(b)
    pc.commit_hit(b, mb)               # touch b: a becomes LRU
    # pressure: a 13-page allocation must reclaim a's pages first
    assert cache.ensure(1, 13 * PAGE)
    assert pc.stats.evictions >= 1
    bids_a, match_a = pc.lookup(a)
    assert match_a < len(a)            # a (partially) evicted
    cache.free_row(1)
    # the evicted prefix re-prefills and re-inserts cleanly
    model.paged_prefill_row(cache, a, 2)
    pc.insert(a, cache, 2)
    cache.free_row(2)
    _check_invariants(cache, pc)


def test_tenant_quota_enforced(model):
    """Per-tenant page quotas (engine/qos.py parse_tenant_quotas
    grammar): over-quota inserts evict the tenant's own zero-ref
    pages first, then skip with quota_rejects."""
    from libsplinter_tpu.engine.qos import parse_tenant_quotas
    assert parse_tenant_quotas("1:2,2:8") == {1: 2, 2: 8}
    with pytest.raises(ValueError):
        parse_tenant_quotas("1=2")
    cache = model.init_paged(4, page=PAGE)
    pc = _attach_pc(cache, tenant_quotas={1: 2})
    ids = (np.arange(1, 25, dtype=np.int32) % 90) + 1   # 3 pages
    model.paged_prefill_row(cache, ids, 0)
    # live row: nothing evictable, so the 3rd page must be rejected
    assert pc.insert(ids, cache, 0, tenant=1) == 2
    assert pc.stats.quota_rejects == 1
    assert pc.tenant_pages() == {1: 2}
    cache.free_row(0)                  # pages go zero-ref
    # a different prefix for the same tenant now evicts its own LRU
    other = ((np.arange(1, 17, dtype=np.int32) * 7) % 90) + 1
    model.paged_prefill_row(cache, other, 1)
    assert pc.insert(other, cache, 1, tenant=1) == 2
    assert pc.tenant_pages() == {1: 2}
    assert pc.stats.evictions >= 2
    cache.free_row(1)
    _check_invariants(cache, pc)


# ------------------------------------------------------------- tp=2 parity

def test_sharded_prefix_sharing_byte_exact_tp2():
    """PR 8 composition: tables/refcounts are host-global and the
    pools shard on kv heads, so prefix sharing under tp=2 (virtual
    8-device CPU mesh) must be byte-exact with the single-chip
    shared path AND with single-chip private serving — including the
    COW page copy running on sharded pools."""
    from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                          make_mesh)
    base = CompletionModel(CFG, buckets=(32,), temp=0.0, seed=1,
                           suffix_buckets=(8,))
    tp = ShardedCompletionModel(CFG, make_mesh(dp=4, tp=2),
                                params=base.params, buckets=(32,),
                                temp=0.0, seed=1, suffix_buckets=(8,))
    prompt = (np.arange(2, 26, dtype=np.int32) % 170) + 1
    seqs = {}
    for tag, m in (("chip", base), ("tp", tp)):
        cache = m.init_paged(4, page=PAGE)
        pc = _attach_pc(cache)
        l0 = m.paged_prefill_row(cache, prompt, 0)
        pc.insert(prompt, cache, 0)
        bids, match = pc.lookup(prompt)
        assert match == len(prompt)
        cache.map_shared(1, bids)
        cache.lengths[1] = len(prompt) - 1
        cache.ensure(1, 40)
        toks = np.full((4,), -1, np.int32)
        toks[0] = int(np.argmax(l0))
        toks[1] = int(prompt[-1])
        out = m.paged_decode_chunk(cache, toks, 6)
        assert pc.stats.cow_copies == 1
        seqs[tag] = ([int(toks[0])] + [int(x) for x in out[0][:5]],
                     [int(x) for x in out[1]])
        donor, joiner = seqs[tag]
        assert joiner == donor, tag
    assert seqs["chip"] == seqs["tp"]


# ------------------------------------------------------ completer end-to-end

def _submit(st, key, prompt):
    st.set(key, prompt)
    st.label_or(key, P.LBL_INFER_REQ)
    st.bump(key)


def _await_ready(st, keys, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(st.labels(k) & P.LBL_READY for k in keys):
            return True
        time.sleep(0.05)
    return False


# 23 chars + BOS = 24 tokens = 3 exact pages: repeats are full-cover
HOT_PROMPT = "abcdefghijklmnopqrstuvw"


def test_continuous_byte_identical_with_midflight_joiner(tmp_path,
                                                         model):
    """Acceptance: greedy decode byte-identical cache-on vs
    cache-off, INCLUDING a joiner that maps a prefix another live
    row is still decoding from (the donor is mid-decode when the
    joiner is submitted)."""
    outs = {}
    for tag, enable in (("off", False), ("on", True)):
        name, st = _mkstore(tmp_path, f"pfx-{tag}")
        try:
            comp = Completer(st, model=model, max_new_tokens=24,
                             flush_tokens=2, template="none",
                             batch_cap=4, page_size=PAGE,
                             prefix_cache=enable)
            comp.attach()
            _submit(st, "donor", HOT_PROMPT)
            th = threading.Thread(
                target=comp.run_continuous,
                kwargs=dict(idle_timeout_ms=20, stop_after=60.0),
                daemon=True)
            th.start()
            # wait until the donor is claimed and streaming, then
            # join with the identical prompt mid-decode
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    if st.value_len("donor") > len(HOT_PROMPT):
                        break
                except KeyError:
                    pass
                time.sleep(0.005)
            _submit(st, "joiner", HOT_PROMPT)
            assert _await_ready(st, ["donor", "joiner"])
            comp.stop()
            th.join(timeout=15)
            outs[tag] = (st.get("donor").rstrip(b"\0"),
                         st.get("joiner").rstrip(b"\0"))
            if enable:
                assert comp.prefix_cache.stats.hits >= 1
                assert comp.prefix_cache.stats.cow_copies >= 1
        finally:
            st.close()
            Store.unlink(name)
    assert outs["on"] == outs["off"]
    # identical prompts, greedy: donor and joiner streams match too
    assert outs["on"][0] == outs["on"][1]


def test_heartbeat_prefix_gauges(tmp_path, model):
    """The prefix_* gauges ride the completer heartbeat (flat fields:
    `spt metrics` renders sptpu_completer_prefix_*, the telemetry
    ring and `spt top` sparkline prefix_hits) and the per-tenant
    residency lands in the tenants section."""
    name, st = _mkstore(tmp_path, "pfx-hb")
    try:
        comp = Completer(st, model=model, max_new_tokens=4,
                         flush_tokens=2, template="none", batch_cap=4,
                         page_size=PAGE)
        comp.attach()
        keys = [f"h/{i}" for i in range(3)]
        for k in keys:
            st.set(k, HOT_PROMPT)
            P.stamp_tenant(st, k, 2)
            st.label_or(k, P.LBL_INFER_REQ)
            st.bump(k)
        th = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=30.0),
            daemon=True)
        th.start()
        assert _await_ready(st, keys)
        # snapshot while the lane is LIVE: shutdown releases the
        # whole pool (the zero-leaked-pages contract), emptying the
        # tree — residency gauges are a live-lane signal
        comp.publish_stats()
        snap = json.loads(st.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))
        comp.stop()
        th.join(timeout=15)
        assert snap["prefix_hits"] >= 1
        assert snap["prefix_misses"] >= 1
        assert snap["prefix_shared_pages"] >= 3
        assert snap["prefix_bytes_saved"] > 0
        for field in ("prefix_evictions", "prefix_cow_copies",
                      "prefix_hit_tokens", "prefix_evictable"):
            assert field in snap
        assert snap["tenants"]["2"]["prefix_pages"] >= 3
        assert snap["tenants"]["2"]["prefix_hit_pages"] >= 3
        # stopped lane: pool returned whole, tree emptied
        assert comp._paged_cache.used_pages == 0
        assert comp.prefix_cache.shared_pages() == 0
    finally:
        st.close()
        Store.unlink(name)


def test_bp_memo_evicts_stale_epochs_first(tmp_path):
    """Regression (ISSUE 14 satellite): under the hard cap the memo
    used next(iter(...)) — insertion order — so a long-lived denied
    request (the exact entry the memo exists for) was evicted while
    freshly-STALE newcomers survived.  Staleness now evicts first."""
    name, st = _mkstore(tmp_path, "bpmemo")
    try:
        comp = Completer(st, generate_fn=lambda p: iter([b"x"]),
                         template="none")
        comp._bp_memo_cap = 3
        keys = [f"m/{i}" for i in range(4)]
        for k in keys:
            st.set(k, "p")
            st.label_or(k, P.LBL_INFER_REQ)
        idxs = [st.find_index(k) for k in keys]
        # entry 0: LIVE (epoch matches), inserted FIRST
        comp._bp_memo[idxs[0]] = (st.epoch_at(idxs[0]), 5)
        # entries 1..3: stale (memo'd epoch is behind the slot's)
        for i in (1, 2, 3):
            e = st.epoch_at(idxs[i])
            st.set(keys[i], "rewritten")   # epoch moves
            comp._bp_memo[idxs[i]] = (e, 5)
        dropped = comp._bound_bp_memo()
        assert dropped == 1
        assert idxs[0] in comp._bp_memo, \
            "live denied entry evicted while stale entries survived"
        assert len(comp._bp_memo) == comp._bp_memo_cap
        # sweep still clears the remaining stale entries wholesale
        comp._sweep_bp_memo()
        assert list(comp._bp_memo) == [idxs[0]]
    finally:
        st.close()
        Store.unlink(name)


# ----------------------------------------------------------------- loadgen

def test_loadgen_shared_prefix_knob_deterministic():
    """`--shared-prefix P:LEN`: seeded and deterministic — two
    generators with one seed draw the identical prompt mix, ~P of it
    from the pooled hot prefixes of exactly LEN chars."""
    from libsplinter_tpu.cli.loadgen import LoadGenerator, TenantSpec

    def prompts(seed):
        gen = LoadGenerator(None, [TenantSpec(1, 10.0)], seed=seed,
                            scenario="shared-prefix",
                            shared_prefix=(0.9, 64))
        return [gen._complete_prompt() for _ in range(80)]

    a, b = prompts(3), prompts(3)
    assert a == b
    pooled = [p for p in a if len(p) == 64]
    assert len(set(pooled)) <= 4
    assert 0.75 <= len(pooled) / len(a) <= 1.0
    assert prompts(4) != a
    with pytest.raises(ValueError):
        LoadGenerator(None, [TenantSpec(1, 1.0)],
                      shared_prefix=(1.5, 64))


def test_loadgen_shared_prefix_reports_hit_rate(tmp_path, model):
    """The shared-prefix scenario against a live continuous completer:
    the summary carries the completer's cache hit rate beside the
    per-tenant SLO rows, and nothing is lost."""
    from libsplinter_tpu.cli.loadgen import LoadGenerator, TenantSpec
    name, st = _mkstore(tmp_path, "pfx-lg", nslots=256)
    try:
        comp = Completer(st, model=model, max_new_tokens=4,
                         flush_tokens=2, template="none", batch_cap=4,
                         page_size=PAGE)
        comp.attach()
        th = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=10, stop_after=120.0),
            daemon=True)
        th.start()
        gen = LoadGenerator(st, [TenantSpec(1, 12.0,
                                            deadline_ms=20_000)],
                            duration_s=2.0, seed=5,
                            scenario="shared-prefix",
                            shared_prefix=(0.9, 3 * PAGE - 1),
                            drain_s=30.0)
        rep = gen.run()
        comp.publish_stats()           # don't race the 2s heartbeat
        pfx = gen._prefix_cache_report()
        comp.stop()
        th.join(timeout=15)
        assert rep["lost"] == 0
        assert rep["ok"] >= 1
        assert pfx is not None and pfx["hits"] >= 1
        assert pfx["hit_rate"] > 0.3
    finally:
        st.close()
        Store.unlink(name)


# ------------------------------------------------------------------- chaos

@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_prefix_map_crash_strands_nothing(tmp_path,
                                                     monkeypatch):
    """The completer.prefix_map fault site: the lane crashes mid
    table-mapping on its first prefix-cache HIT (request claimed,
    refcount bumps about to happen).  `spt supervise` restarts it;
    pool, refcounts, and tree died with the process, so the restarted
    lane serves the reclaimed request from a clean pool — no stranded
    refcounts, no lost request, and a THIRD request round-trips."""
    import os

    from libsplinter_tpu.engine.supervisor import Supervisor

    name, st = _mkstore(tmp_path, "pfx-chaos", nslots=256)
    child = os.path.join(os.path.dirname(__file__), "chaos_child.py")
    monkeypatch.setenv("SPTPU_FAULT", "completer.prefix_map:crash@1")
    monkeypatch.setenv("SPTPU_CHAOS_RUN_S", "600")
    try:
        # both submitted upfront with one prompt: the first admission
        # misses (inserts), the second HITS -> crash mid-mapping
        _submit(st, "c1", HOT_PROMPT)
        _submit(st, "c2", HOT_PROMPT)
        holder: dict = {}

        def spawn(lane):
            return subprocess.Popen(
                [sys.executable, child, "completer_prefix", name],
                env=holder["sup"]._child_env(lane))

        sup = Supervisor(name, lanes=("completer",), spawn_fn=spawn,
                         store=st, backoff_base_ms=100,
                         backoff_max_ms=2000, breaker_threshold=8,
                         breaker_window_s=120, startup_grace_s=300)
        holder["sup"] = sup
        t = threading.Thread(target=sup.run,
                             kwargs={"poll_interval_s": 0.1,
                                     "stop_after": 240.0})
        t.start()
        try:
            assert _await_ready(st, ["c1", "c2"], timeout=180), \
                sup.lanes
            assert sup.lanes["completer"].restarts >= 1
            # post-crash hit path works too (generation-2 lane,
            # fault stripped): same prompt, fresh tree
            _submit(st, "c3", HOT_PROMPT)
            assert _await_ready(st, ["c3"], timeout=120)
            for k in ("c1", "c2", "c3"):
                assert not st.labels(k) & (P.LBL_INFER_REQ
                                           | P.LBL_SERVICING)
        finally:
            sup.stop()
            t.join()
            sup.shutdown()
    finally:
        st.close()
        Store.unlink(name)
