"""Search daemon: request protocol, query coalescing, result commit,
stage quantiles, and the CLI dispatch path.  `make search-check` runs
this file (the coalescing smoke test is the acceptance gate: N
concurrent clients must cost << N device dispatches)."""
from __future__ import annotations

import contextlib
import io
import json
import threading
import time

import numpy as np
import pytest

from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.searcher import (QB_BUCKETS, Searcher,
                                             daemon_live, submit_search)
from libsplinter_tpu.utils.trace import tracer


@pytest.fixture
def traced():
    """Enable the process tracer for one test, restoring cleanly."""
    prev = tracer.enabled
    tracer.enabled = True
    yield tracer
    tracer.enabled = prev
    tracer.reset()


def _fill_docs(store, n, rng, dim=None):
    dim = dim or store.vec_dim
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    for i in range(n):
        store.set(f"doc/{i}", f"text {i}")
        store.vec_set(f"doc/{i}", vecs[i])
    return vecs


def _request(store, key, qvec, k=5, bloom=0):
    store.set(key, json.dumps({"k": k, "bloom": bloom}))
    store.vec_set(key, qvec)
    store.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
    store.bump(key)


def _result(store, key):
    return json.loads(
        store.get(P.search_result_key(store.find_index(key)))
        .rstrip(b"\0"))


def _dense_ref(lane, q, exclude=()):
    norms = np.linalg.norm(lane, axis=1) * np.linalg.norm(q)
    with np.errstate(invalid="ignore"):
        s = np.where(norms > 0, lane @ q / np.maximum(norms, 1e-12),
                     -np.inf)
    s[list(exclude)] = -np.inf
    return s


def test_coalesces_concurrent_requests(store):
    """Acceptance: 32 in-flight queries -> device dispatch count <=
    ceil(32 / QB), with every per-request result correct."""
    rng = np.random.default_rng(1)
    _fill_docs(store, 64, rng)
    sr = Searcher(store)
    sr.attach()
    qs = rng.normal(size=(32, store.vec_dim)).astype(np.float32)
    keys = [f"__sqtmp_{1000 + i}" for i in range(32)]
    for key, q in zip(keys, qs):
        _request(store, key, q)
    req_slots = {store.find_index(k) for k in keys}

    served = sr.run_once()
    assert served == 32
    assert sr.stats.dispatches <= -(-32 // max(QB_BUCKETS)) + 1
    assert sr.stats.dispatches == 1            # 32 fits one bucket
    assert sr.stats.coalesced_max == 32
    assert sr.stats.coalesce_ratio() == 32.0

    lane = np.array(store.vectors)
    for key, q in zip(keys, qs):
        rec = _result(store, key)
        ref = _dense_ref(lane, q, exclude=req_slots)
        order = np.argsort(-ref)[:5]
        assert rec["i"] == list(order)
        np.testing.assert_allclose(rec["s"], ref[order], rtol=1e-4)
        assert rec["keys"] == [store.key_at(int(i)) for i in order]
        assert not store.labels(key) & (P.LBL_SEARCH_REQ | P.LBL_WAITING)


def test_qb_chunk_plan():
    """Query-count decomposition stays on the bucket schedule with
    padding waste <= 2x — 40 queries must NOT pad to one 256 batch."""
    from libsplinter_tpu.engine.searcher import _qb_chunks
    assert _qb_chunks(1) == [8]
    assert _qb_chunks(8) == [8]
    assert _qb_chunks(32) == [32]
    assert _qb_chunks(40) == [32, 8]
    assert _qb_chunks(200) == [256]            # waste 1.28x: one batch
    assert _qb_chunks(300) == [256, 32, 8, 8]
    assert _qb_chunks(600) == [256, 256, 32, 32, 32]
    for nq in range(1, 700):
        plan = _qb_chunks(nq)
        assert sum(plan) >= nq
        assert sum(plan) <= max(2 * nq, 8), (nq, plan)


def test_system_rows_never_surface(store):
    """Request slots hold query vectors and heartbeat rows hold JSON;
    none may appear in results even for a query identical to another
    pending query."""
    rng = np.random.default_rng(2)
    _fill_docs(store, 16, rng)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    _request(store, "__sqtmp_a", q)
    _request(store, "__sqtmp_b", q)            # identical query
    assert sr.run_once() == 2
    for key in ("__sqtmp_a", "__sqtmp_b"):
        rec = _result(store, key)
        assert all(k.startswith("doc/") for k in rec["keys"])


def test_bloom_groups_and_masks(store):
    """Requests with different bloom prefilters group into separate
    dispatches, each honoring its own mask."""
    rng = np.random.default_rng(3)
    _fill_docs(store, 24, rng)
    marked = [f"doc/{i}" for i in (3, 7, 11)]
    for key in marked:
        store.label_or(key, P.LBL_CHUNK)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    _request(store, "__sqtmp_all", q, k=20, bloom=0)
    _request(store, "__sqtmp_chunk", q, k=20, bloom=P.LBL_CHUNK)
    assert sr.run_once() == 2
    assert sr.stats.dispatches == 2            # one per mask group
    rec = _result(store, "__sqtmp_chunk")
    assert sorted(rec["keys"]) == sorted(marked)
    assert len(_result(store, "__sqtmp_all")["keys"]) > 3


def test_fast_flag_rides_the_request(store):
    """--fast requests bf16 scoring server-side: fast and exact
    requests group into separate dispatches (matmul precision is a
    per-program property), and both come back correct."""
    rng = np.random.default_rng(14)
    _fill_docs(store, 16, rng)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    store.set("__sqtmp_f", json.dumps({"k": 3, "fast": True}))
    store.vec_set("__sqtmp_f", q)
    store.label_or("__sqtmp_f", P.LBL_SEARCH_REQ)
    store.bump("__sqtmp_f")
    _request(store, "__sqtmp_x", q, k=3)
    assert sr.run_once() == 2
    assert sr.stats.dispatches == 2            # one per precision group
    assert (_result(store, "__sqtmp_f")["i"]
            == _result(store, "__sqtmp_x")["i"])   # cpu: same math


def test_bad_request_params_fail_fast(store):
    """Malformed params can never succeed: the daemon answers with an
    error result and clears the label instead of spinning."""
    rng = np.random.default_rng(4)
    _fill_docs(store, 8, rng)
    sr = Searcher(store)
    sr.attach()
    key = "__sqtmp_bad"
    store.set(key, "not json at all")
    store.vec_set(key, rng.normal(size=store.vec_dim)
                  .astype(np.float32))
    store.label_or(key, P.LBL_SEARCH_REQ)
    store.bump(key)
    assert sr.run_once() == 0
    assert sr.stats.parse_errors == 1
    assert "err" in _result(store, key)
    assert not store.labels(key) & P.LBL_SEARCH_REQ


def test_vectorless_request_fails_fast(store):
    rng = np.random.default_rng(5)
    _fill_docs(store, 8, rng)
    sr = Searcher(store)
    sr.attach()
    key = "__sqtmp_novec"
    store.set(key, json.dumps({"k": 3}))       # no vec_set
    store.label_or(key, P.LBL_SEARCH_REQ)
    store.bump(key)
    assert sr.run_once() == 0
    assert "err" in _result(store, key)
    assert not store.labels(key) & P.LBL_SEARCH_REQ


def test_oversized_k_clamped_to_lane(store):
    """A request k beyond nslots (or the CLI's x8 growth crossing the
    lane) must clamp the fetch, never trace top_k(k > rows) and
    poison-pill the drain loop."""
    rng = np.random.default_rng(13)
    _fill_docs(store, 8, rng)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    _request(store, "__sqtmp_huge", q, k=store.nslots * 20)
    assert sr.run_once() == 1                  # serviced, not crashed
    rec = _result(store, "__sqtmp_huge")
    assert len(rec["keys"]) == 8
    assert rec["fetched"] <= store.nslots


def test_k_larger_than_candidates(store):
    rng = np.random.default_rng(6)
    _fill_docs(store, 4, rng)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    _request(store, "__sqtmp_big", q, k=50)
    assert sr.run_once() == 1
    rec = _result(store, "__sqtmp_big")
    assert len(rec["keys"]) == 4               # every doc, nothing more
    assert rec["n"] == 4                       # candidates exhausted
    assert rec["n"] < rec["fetched"]           # client growth stops


@pytest.mark.obs
def test_heartbeat_quantiles_and_liveness(traced):
    """With tracing on, the heartbeat carries SEARCH_STAGES quantile
    summaries (what `spt metrics` renders) and its ts drives
    daemon_live.  Own store: the traced heartbeat needs max_val
    headroom beyond the small fixture's 1 KiB (publish_heartbeat would
    degrade the quantiles section away, which is exactly what the
    fixture-sized store SHOULD do — but not what this test checks)."""
    import os
    import uuid

    name = f"/spt-srhb-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    Store.unlink(name)
    store = Store.create(name, nslots=256, max_val=4096, vec_dim=32)
    try:
        rng = np.random.default_rng(7)
        _fill_docs(store, 16, rng)
        sr = Searcher(store)
        sr.attach()
        assert not daemon_live(store)          # no heartbeat yet
        _request(store, "__sqtmp_q", rng.normal(size=store.vec_dim)
                 .astype(np.float32))
        assert sr.run_once() == 1
        sr.publish_stats()
        assert daemon_live(store)
        snap = json.loads(store.get(P.KEY_SEARCH_STATS).rstrip(b"\0"))
        assert snap["served"] == 1
        for stage in P.SEARCH_STAGES:
            assert stage in snap["quantiles"], snap["quantiles"].keys()
            assert "p50_ms" in snap["quantiles"][stage]
        assert snap["lane"]["full_uploads"] == 1

        # and the same quantiles render through `spt metrics`
        from libsplinter_tpu.cli.main import COMMANDS, Session
        ses = Session(name)
        try:
            fn, _, _ = COMMANDS["metrics"]
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                fn(ses, [])
            out = buf.getvalue()
            assert "sptpu_searcher_served 1" in out
            assert "sptpu_searcher_lane_full_uploads 1" in out
            for stage in P.SEARCH_STAGES:
                assert (f'daemon="searcher",stage="{stage}"' in out
                        ), f"{stage} quantiles missing from exposition"
        finally:
            ses.close()
    finally:
        store.close()
        Store.unlink(name)


@pytest.mark.obs
def test_traced_request_hits_flight_recorder(store, traced):
    """A stamped request's wake->commit journey lands in the searcher's
    ring under the SEARCH_STAGES event names."""
    rng = np.random.default_rng(8)
    _fill_docs(store, 8, rng)
    sr = Searcher(store)
    sr.attach()
    key = "__sqtmp_tr"
    store.set(key, json.dumps({"k": 2}))
    store.vec_set(key, rng.normal(size=store.vec_dim)
                  .astype(np.float32))
    store.label_or(key, P.LBL_SEARCH_REQ)
    tid = P.stamp_trace(store, key)
    store.bump(key)
    assert sr.run_once() == 1
    recs = sr.recorder.tail(4)
    assert [r["id"] for r in recs] == [tid]
    assert [e[0] for e in recs[0]["events"]] == list(P.SEARCH_STAGES)
    # stamp consumed: companion key + TRACED bit gone
    assert not store.labels(key) & P.LBL_TRACED


def test_raced_rewrite_not_committed(store):
    """A request slot rewritten between gather and commit must NOT get
    the stale result: the commit is epoch-gated like the embedder's."""
    rng = np.random.default_rng(9)
    _fill_docs(store, 8, rng)
    sr = Searcher(store)
    sr.attach()
    key = "__sqtmp_race"
    _request(store, key,
             rng.normal(size=store.vec_dim).astype(np.float32))

    real_service = sr._service

    def racing_service(reqs):
        store.set(key, json.dumps({"k": 3}))   # epoch moves mid-flight
        return real_service(reqs)

    sr._service = racing_service
    assert sr.run_once() == 0
    assert sr.stats.raced == 1
    assert store.labels(key) & P.LBL_SEARCH_REQ   # still pending
    sr._service = real_service
    assert sr.run_once() == 1                  # retried clean


def test_submit_search_round_trip(store):
    """Client helper against a live daemon thread: label, wait, read."""
    rng = np.random.default_rng(10)
    vecs = _fill_docs(store, 12, rng)
    sr = Searcher(store)
    sr.attach()
    t = threading.Thread(target=sr.run,
                         kwargs={"stop_after": 10.0,
                                 "idle_timeout_ms": 20})
    t.start()
    try:
        key = "__sqtmp_cli"
        store.set(key, "placeholder")
        store.vec_set(key, vecs[3])
        rec = submit_search(store, key, 3, timeout_ms=8000)
        assert rec is not None and rec["keys"][0] == "doc/3"
    finally:
        sr.stop()
        t.join()
    assert sr.stats.wakes >= 1                 # signal path, not sweep


def test_cli_search_dispatches_to_daemon(store, monkeypatch):
    """cmd_search routes through a live daemon (heartbeat fresh) and
    renders its rows; the daemon's served counter proves the dispatch
    took the server-side path."""
    from libsplinter_tpu.cli.main import COMMANDS, Session

    rng = np.random.default_rng(11)
    vecs = _fill_docs(store, 20, rng)
    sr = Searcher(store)
    sr.attach()

    # an embedding daemon stand-in: answers the scratch-key embed with
    # a vector aimed at doc/7
    from libsplinter_tpu.engine.embedder import Embedder
    emb = Embedder(store, encoder_fn=lambda texts: np.tile(
        vecs[7], (len(texts), 1)))
    emb.attach()

    stop = threading.Event()

    def daemons():
        while not stop.is_set():
            emb.run_once()
            sr.run_once()
            sr.publish_stats()
            time.sleep(0.005)

    t = threading.Thread(target=daemons)
    t.start()
    try:
        ses = Session(store.name)
        fn, _, _ = COMMANDS["search"]
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            fn(ses, ["--json", "--limit", "2", "find doc seven"])
        rows = json.loads(buf.getvalue())
    finally:
        stop.set()
        t.join()
        ses.close()
    assert rows and rows[0]["key"] == "doc/7"
    assert rows[0]["similarity"] == pytest.approx(1.0, abs=1e-5)
    assert sr.stats.served >= 1                # daemon path was used
    # the CLI never staged a client-side lane for this query
    assert ses._lane is None


def test_daemon_live_dead_pid_reads_dead_instantly(store):
    """The staleness fix: a fresh heartbeat ts whose publisher pid is
    gone must NOT hold daemon_live true for max_age_s — the CLI's
    fallback to local scoring should be instant after a crash."""
    import os
    import subprocess
    import sys

    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    snap = {"ts": time.time(), "pid": proc.pid, "served": 0}
    store.set(P.KEY_SEARCH_STATS, json.dumps(snap))
    assert not daemon_live(store)
    # same snapshot with a live pid (ours) is live
    snap["pid"] = os.getpid()
    store.set(P.KEY_SEARCH_STATS, json.dumps(snap))
    assert daemon_live(store)
    # pre-pid-format heartbeats fall back to age-only (compat)
    store.set(P.KEY_SEARCH_STATS, json.dumps({"ts": time.time()}))
    assert daemon_live(store)
    store.set(P.KEY_SEARCH_STATS,
              json.dumps({"ts": time.time() - 3600}))
    assert not daemon_live(store)


def test_submit_search_repulses_once_at_half_deadline(store):
    """A pulse that races the daemon's signal_wait re-arm used to cost
    the whole timeout; submit_search now re-bumps exactly once when
    half the deadline is gone with the label still set."""
    bumps = []
    orig = store.bump
    store.bump = lambda key: (bumps.append(key), orig(key))[1]
    try:
        store.set("__sqtmp_rp", "x")
        store.vec_set("__sqtmp_rp", np.ones(store.vec_dim, np.float32))
        rec = submit_search(store, "__sqtmp_rp", 3, timeout_ms=250)
    finally:
        store.bump = orig
    assert rec is None                 # no daemon: times out
    assert bumps.count("__sqtmp_rp") == 2   # initial + ONE re-pulse


def test_sweep_fault_site_contained(store):
    """`searcher.sweep` chaos reachability (splint SPL104): an
    injected raise fires out of sweep_results itself; in production
    the run loop's cycle firewall absorbs it (drain_faults) and the
    next heartbeat cadence retries — here we pin that the site is
    live and that the sweep runs clean once the hit window passes."""
    from libsplinter_tpu.utils import faults

    rng = np.random.default_rng(23)
    _fill_docs(store, 4, rng)
    sr = Searcher(store)
    sr.attach()
    faults.arm("searcher.sweep:raise@1")
    try:
        assert faults.registered_sites() == ("searcher.sweep",)
        with pytest.raises(faults.FaultInjected):
            sr.sweep_results()
        assert sr.sweep_results() == 0   # window passed: clean sweep
        assert faults.stats()["searcher.sweep"]["fired"] == 1
    finally:
        faults.disarm()


def test_result_ttl_sweep_reaps_orphans(store):
    """A client that times out never consumes its __sr_ row; the
    periodic sweep retires rows past the TTL and rows whose request
    slot epoch moved on — and leaves live rows alone."""
    rng = np.random.default_rng(21)
    _fill_docs(store, 12, rng)
    sr = Searcher(store)
    sr.attach()
    for name in ("__sqtmp_o1", "__sqtmp_o2", "__sqtmp_keep"):
        _request(store, name, rng.normal(size=store.vec_dim)
                 .astype(np.float32))
    assert sr.run_once() == 3
    # all three rows exist; nobody consumed them
    rows = [k for k in store.list()
            if k.startswith(P.SEARCH_RESULT_PREFIX)]
    assert len(rows) == 3

    # o2's slot is rewritten (a NEW request will own it): epoch moved
    store.set("__sqtmp_o2", "brand new content")
    assert sr.sweep_results() == 1     # only the epoch-moved row
    assert sr.stats.results_reaped == 1

    # TTL expiry: pretend 10 minutes pass — both leftovers reap
    assert sr.sweep_results(now=time.time() + 600) == 2
    assert not [k for k in store.list()
                if k.startswith(P.SEARCH_RESULT_PREFIX)]

    # a fresh result row within TTL with an unmoved slot survives
    _request(store, "__sqtmp_keep",
             rng.normal(size=store.vec_dim).astype(np.float32))
    assert sr.run_once() == 1
    assert sr.sweep_results() == 0


def test_per_batch_failure_fails_only_that_batch(store, monkeypatch):
    """Acceptance: a device failure injected mid-_service fails only
    the faulted batch's requests with error records; the sibling batch
    commits normally and the daemon's loop never unwinds."""
    from libsplinter_tpu.engine import resident
    from libsplinter_tpu.utils import faults

    rng = np.random.default_rng(22)
    _fill_docs(store, 16, rng)
    marked = [f"doc/{i}" for i in (2, 5)]
    for key in marked:
        store.label_or(key, P.LBL_CHUNK)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    # two bloom groups -> two batches, dispatched [poison, fine].
    # Site hit order: dispatch(b1)=1, dispatch(b2)=2, then b1's
    # degradation ladder re-hits dispatch at 3 (unfused) and 4
    # (per-request) — so select@1 fails b1's fetch and dispatch@3-4
    # defeats exactly b1's ladder, leaving b2 untouched.
    # That hit order is only guaranteed when batches resolve at
    # flush(): the window's ready-probe (drain_ready) resolves an
    # already-COMPLETED batch at the next push, so on a fast or
    # lightly-loaded host b1's select + ladder can fire before b2's
    # dispatch and the armed 3-4 window lands on the wrong hits.
    # Forcing every entry not-ready defers resolution to flush()
    # (dispatch order) — same per-batch domains, deterministic counts.
    monkeypatch.setattr(resident.CallbackWindow, "_entry_ready",
                        lambda self, entry: False)
    _request(store, "__sqtmp_poison", q, k=3, bloom=0)
    _request(store, "__sqtmp_fine", q, k=3, bloom=P.LBL_CHUNK)
    faults.arm("searcher.select:raise@1,searcher.dispatch:raise@3-4")
    try:
        served = sr.run_once()
    finally:
        faults.disarm()
    assert served == 1                 # the healthy batch committed
    assert sr.stats.batch_faults == 1
    assert sr.stats.req_failures == 1
    rec_bad = _result(store, "__sqtmp_poison")
    assert "err" in rec_bad            # failed WITH an error record
    rec_ok = _result(store, "__sqtmp_fine")
    assert sorted(rec_ok["keys"]) == sorted(marked)
    for key in ("__sqtmp_poison", "__sqtmp_fine"):
        assert not store.labels(key) & P.LBL_SEARCH_REQ


def test_batch_failure_recovers_unfused(store):
    """One transient device failure: the unfused retry serves the
    batch's requests correctly — no client ever sees it."""
    from libsplinter_tpu.utils import faults

    rng = np.random.default_rng(23)
    _fill_docs(store, 16, rng)
    sr = Searcher(store)
    sr.attach()
    q = rng.normal(size=store.vec_dim).astype(np.float32)
    _request(store, "__sqtmp_tr1", q, k=4)
    faults.arm("searcher.select:raise@1")
    try:
        served = sr.run_once()
    finally:
        faults.disarm()
    assert served == 1
    assert sr.stats.retried_unfused == 1
    lane = np.array(store.vectors)
    ref = _dense_ref(lane, q,
                     exclude={store.find_index("__sqtmp_tr1")})
    rec = _result(store, "__sqtmp_tr1")
    assert rec["i"] == list(np.argsort(-ref)[:4])


def test_cli_search_local_flag_bypasses_daemon(store):
    """--local forces client-side scoring even with a fresh daemon
    heartbeat."""
    from libsplinter_tpu.cli.main import COMMANDS, Session

    rng = np.random.default_rng(12)
    vecs = _fill_docs(store, 10, rng)
    sr = Searcher(store)
    sr.attach()
    sr.publish_stats()                         # heartbeat says "live"

    from libsplinter_tpu.engine.embedder import Embedder
    emb = Embedder(store, encoder_fn=lambda texts: np.tile(
        vecs[2], (len(texts), 1)))
    emb.attach()
    stop = threading.Event()

    def embed_only():
        while not stop.is_set():
            emb.run_once()
            time.sleep(0.005)

    t = threading.Thread(target=embed_only)
    t.start()
    try:
        ses = Session(store.name)
        fn, _, _ = COMMANDS["search"]
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            fn(ses, ["--json", "--local", "--limit", "1", "query"])
        rows = json.loads(buf.getvalue())
    finally:
        stop.set()
        t.join()
        ses.close()
    assert rows and rows[0]["key"] == "doc/2"
    assert sr.stats.served == 0                # daemon untouched
    assert rows[0]["distance"] is not None     # local path scores both
