"""Daemon-level tests of the embedding engine with a fake encoder — the
test tier the reference lacks entirely (SURVEY.md §4 'Daemon-level
testing: none automated — a gap we should close with a fake-encoder
fixture')."""
import threading
import time

import numpy as np
import pytest

import libsplinter_tpu as sp
from libsplinter_tpu import Store
from libsplinter_tpu.engine import protocol as P
from libsplinter_tpu.engine.embedder import Embedder


def fake_encoder(texts):
    """Deterministic 'embedding': vec[0] = len(text), vec[1] = word count."""
    out = np.zeros((len(texts), 32), np.float32)
    for i, t in enumerate(texts):
        out[i, 0] = len(t)
        out[i, 1] = len(t.split())
        out[i, 2] = 1.0
    return out


@pytest.fixture
def embedder(store):
    emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
    emb.attach()
    return emb


def _request(store, key, text):
    store.set(key, text)
    store.set_type(key, sp.T_VARTEXT)
    store.label_or(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
    store.bump(key)


def test_oneshot_embeds_labelled_key(store, embedder):
    _request(store, "doc1", "hello tpu world")
    n = embedder.run_once()
    assert n == 1
    v = store.vec_get("doc1")
    assert v[0] == len("hello tpu world")
    assert v[1] == 3
    # WAITING and EMBED_REQ cleared after the vector lands
    assert store.labels("doc1") & (P.LBL_EMBED_REQ | P.LBL_WAITING) == 0


def test_batch_drain_embeds_all(store, embedder):
    for i in range(20):
        _request(store, f"doc{i}", f"text number {i}")
    n = embedder.run_once()
    assert n == 20
    assert embedder.stats.batches == 1        # one micro-batch, not 20
    for i in range(20):
        assert store.vec_get(f"doc{i}")[2] == 1.0


def test_unlabelled_keys_ignored(store, embedder):
    store.set("plain", "no label here")
    n = embedder.run_once()
    assert n == 0
    assert store.vec_get("plain")[2] == 0.0


def test_no_rembedding_at_same_epoch(store, embedder):
    _request(store, "doc", "stable text")
    assert embedder.run_once() == 1
    store.label_or("doc", P.LBL_EMBED_REQ)    # re-label without rewrite
    assert embedder.run_once() == 0           # epoch unchanged -> skip


def test_rewrite_triggers_rembedding(store, embedder):
    _request(store, "doc", "v1")
    assert embedder.run_once() == 1
    _request(store, "doc", "version two")
    assert embedder.run_once() == 1
    assert store.vec_get("doc")[0] == len("version two")


def test_ctx_exceeded_protocol(store, embedder):
    long_text = "word " * 100                  # >= 0.9 * max_ctx=64 words
    _request(store, "huge", long_text)
    n = embedder.run_once()
    assert n == 0
    assert embedder.stats.ctx_exceeded == 1
    # marker label set, request labels cleared, vector zeroed, diagnostic
    labels = store.labels("huge")
    assert labels & P.LBL_CTX_EXCEEDED
    assert not labels & P.LBL_EMBED_REQ
    assert store.vec_get("huge")[2] == 0.0
    assert store.get("huge") == P.CTX_EXCEEDED_DIAGNOSTIC


def test_vector_training_write_once(store):
    emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64,
                   vector_training=True)
    emb.attach()
    _request(store, "doc", "first")
    assert emb.run_once() == 1
    first = store.vec_get("doc").copy()
    _request(store, "doc", "second version")
    assert emb.run_once() == 0                 # write-once gate holds
    assert emb.stats.skipped_write_once == 1
    np.testing.assert_array_equal(store.vec_get("doc"), first)


def test_raced_write_not_committed(store, embedder):
    """A slot rewritten between gather and commit must not get the stale
    vector (the reference's epoch+2 check, batched)."""
    _request(store, "doc", "short")
    rows = [store.find_index("doc")]
    keep, texts, epochs = embedder._gather(rows)
    store.set("doc", "changed meanwhile!")     # invalidate the epoch
    res = store.vec_commit_batch(
        np.asarray(keep, np.uint32), np.asarray(epochs, np.uint64),
        fake_encoder(texts))
    assert res[0] != 0
    assert store.vec_get("doc")[2] == 0.0


def test_backfill_sweep(store, embedder):
    for i in range(5):
        store.set(f"bf{i}", f"backfill {i}")
        store.set_type(f"bf{i}", sp.T_VARTEXT)
    store.set("notext", b"binary")             # not VARTEXT: skipped
    n = embedder.backfill()
    assert n == 5
    for i in range(5):
        assert store.vec_get(f"bf{i}")[2] == 1.0
    assert store.vec_get("notext")[2] == 0.0


def test_cold_start_baseline(store):
    """Keys already carrying vectors are not re-embedded on daemon start
    (reference: splinference.cpp:463-493)."""
    store.set("old", "already embedded")
    store.label_or("old", P.LBL_EMBED_REQ)
    store.vec_set("old", np.full(32, 9.0, np.float32))
    emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
    emb.attach()
    assert emb.run_once() == 0
    assert store.vec_get("old")[0] == 9.0


def test_done_lane_pulsed(store, embedder):
    store.set(P.KEY_DONE_LANE, b"")
    store.watch_register(P.KEY_DONE_LANE, 5)
    _request(store, "doc", "ping")
    embedder.run_once()
    assert store.signal_count(5) >= 1


def test_event_driven_loop_end_to_end(store):
    """Full daemon loop in a thread: client request -> signal wake ->
    batched embed -> client observes vector."""
    emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
    emb.attach()
    t = threading.Thread(target=emb.run,
                         kwargs=dict(idle_timeout_ms=50, stop_after=3.0))
    t.start()
    try:
        time.sleep(0.05)
        client = Store.open(store.name)
        _request(client, "live-doc", "event driven embedding")
        deadline = time.monotonic() + 2.5
        while time.monotonic() < deadline:
            if client.vec_get("live-doc")[2] == 1.0:
                break
            time.sleep(0.01)
        v = client.vec_get("live-doc")
        client.close()
        assert v[0] == len("event driven embedding")
        assert emb.stats.wakes >= 1
    finally:
        emb.stop()
        t.join()


def test_fused_model_path_end_to_end(store):
    """Real-model drain: the fused guard+tokenize path (one native batch
    call feeding both the ctx decision and the encoder ids) must embed
    short texts and ctx-exceed long ones exactly like the two-pass flow."""
    from libsplinter_tpu.models import EmbeddingModel, EncoderConfig
    import jax.numpy as jnp

    cfg = EncoderConfig.tiny(out_dim=store.vec_dim, max_len=64,
                             dtype=jnp.float32)
    model = EmbeddingModel(cfg, buckets=(16, 64))
    emb = Embedder(store, model=model, max_ctx=64)
    emb.attach()
    # guard threshold = 0.9 * 64 = 57 tokens
    store.set("short", "a few ordinary words")
    store.set_type("short", sp.T_VARTEXT)
    store.label_or("short", P.LBL_EMBED_REQ)
    store.set("long", "word " * 80)
    store.set_type("long", sp.T_VARTEXT)
    store.label_or("long", P.LBL_EMBED_REQ)
    n = emb.run_once()
    assert n == 1
    assert emb.stats.ctx_exceeded == 1
    assert np.abs(store.vec_get("short")).max() > 0
    assert np.abs(store.vec_get("long")).max() == 0
    assert store.labels("long") & P.LBL_CTX_EXCEEDED
    # parity: the decision matches the pure two-pass predicate
    assert not emb._too_long("a few ordinary words")
    assert emb._too_long("word " * 80)


# ------------------------------------------------ failure domains

def test_encoder_failure_degrades_and_retries(store):
    """A raising encoder fails its batch ALONE: the drain survives,
    the batch cap halves, and the next drain (fault cleared) retries
    the same rows to success — clients never see the transient."""
    calls = {"n": 0}

    def flaky(texts):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient device loss")
        return fake_encoder(texts)

    emb = Embedder(store, encoder_fn=flaky, max_ctx=64, batch_cap=8)
    emb.attach()
    for i in range(4):
        _request(store, f"d{i}", f"text {i}")
    assert emb.run_once() == 0                # batch failed, absorbed
    assert emb.stats.batch_faults == 1
    assert emb.effective_batch_cap == 2       # halved (4-row batch)
    for i in range(4):                        # still pending, not wedged
        assert store.labels(f"d{i}") & P.LBL_EMBED_REQ
    assert emb.run_once() == 4                # clean retry commits all
    for i in range(4):
        assert store.vec_get(f"d{i}")[2] == 1.0
    assert emb.run_once() == 0                # idle
    assert emb.effective_batch_cap > 2        # cap restoring


def test_poison_row_fails_terminally_after_strikes(store):
    """A row whose batch fails ROW_STRIKE_LIMIT times is failed
    terminally: labels cleared + bump, so a blocked client unblocks
    and degrades instead of waiting forever."""
    from libsplinter_tpu.engine.embedder import ROW_STRIKE_LIMIT

    def always_bad(texts):
        raise RuntimeError("poison")

    emb = Embedder(store, encoder_fn=always_bad, max_ctx=64)
    emb.attach()
    _request(store, "bad", "unembeddable")
    for _ in range(ROW_STRIKE_LIMIT):
        assert emb.run_once() == 0
    assert emb.stats.embed_failed == 1
    assert not store.labels("bad") & (P.LBL_EMBED_REQ | P.LBL_WAITING)
    assert np.abs(store.vec_get("bad")).max() == 0
    assert emb.run_once() == 0                # no respin on the corpse
    # a rewrite re-candidates the row with a clean slate
    good = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
    good.attach()
    _request(store, "bad", "now fine")
    assert good.run_once() == 1
    assert store.vec_get("bad")[2] == 1.0


def test_rewrite_racing_final_strike_keeps_new_request(store, embedder):
    """Epoch gate on the terminal strike path: a client rewrite that
    lands while the old text's batch is failing its final strike must
    NOT have its labels cleared — the new request stays live and
    embeds on the next drain."""
    from libsplinter_tpu.engine.embedder import ROW_STRIKE_LIMIT

    _request(store, "r", "old text")
    [idx] = store.enumerate_indices(P.LBL_EMBED_REQ)
    old_epoch = store.epoch_at(idx)
    # the rewrite lands first; the old text's batch then strikes out
    # carrying the OLD epoch (gathered before the rewrite)
    _request(store, "r", "new text")
    for _ in range(ROW_STRIKE_LIMIT):
        embedder._on_batch_error([idx], [old_epoch],
                                 RuntimeError("poison"))
    assert embedder.stats.embed_failed == 0   # gate held
    assert store.labels("r") & (P.LBL_EMBED_REQ | P.LBL_WAITING)
    assert embedder.run_once() == 1           # the NEW text embeds
    assert store.vec_get("r")[0] == len("new text")


def test_injected_commit_fault_contained(store):
    """An injected store.vec_commit failure rides the same per-batch
    firewall as an encode failure (the daemon stays up, rows retry)."""
    from libsplinter_tpu.utils import faults

    emb = Embedder(store, encoder_fn=fake_encoder, max_ctx=64)
    emb.attach()
    _request(store, "c1", "hello")
    faults.arm("store.vec_commit:raise@1")
    try:
        assert emb.run_once() == 0
        assert emb.stats.batch_faults == 1
        assert emb.run_once() == 1            # fault window passed
    finally:
        faults.disarm()
    assert store.vec_get("c1")[2] == 1.0
