"""Quantized paged KV pool (int8, per-page scales) + self-drafting
speculative decode through the paged kernel (PR 9).

Numeric tolerance contract (the "stated tolerance" of the acceptance
criteria): per-page symmetric int8 puts every stored element within
d/2 of its float value, d = page-absmax/127, i.e. <= 0.4% of the
page's max magnitude.  On unit-scale random K/V (the tests' inputs),
attention outputs of the int8 paged kernel stay within ATOL=0.05 of
the f32 paged kernel (measured headroom ~4x), and the in-register
dequant itself is EXACT against running the f32 kernel over
host-dequantized pools (1e-5).  Token-level, greedy int8 paged decode
agrees with f32 paged decode on the tiny model (asserted >= 75% over
16 tokens; empirically 100%).

`make quant-check` runs this file's fast tier plus
scripts/quant_pool_bytes_check.py (int8 pool bytes == 1/2 bf16 ==
1/4 f32 for the same page count, measured from placed buffers).
"""
from __future__ import annotations

import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from libsplinter_tpu.models.decoder import (CompletionModel,
                                            DecoderConfig, PagedKVCache,
                                            _quant_append)
from libsplinter_tpu.models.speculative import (SpeculativeCompletionModel,
                                                self_draft_model)
from libsplinter_tpu.ops.paged_attention import (dequantize_pool,
                                                 paged_attention)

ATOL = 0.05          # int8-vs-f32 attention output bound (unit-scale)
DEQ_TOL = 2e-5       # in-register dequant vs host dequant (exactness)


def _build_paged(rng, lengths, *, KH, D, page, P, shuffle=True):
    """Random float pools + tables for the given ragged lengths
    (mirrors test_paged_attention._build_paged)."""
    B = len(lengths)
    n_blocks = 1 + sum(-(-int(l) // page) or 1 for l in lengths)
    kp = rng.randn(n_blocks, KH, page, D).astype(np.float32)
    vp = rng.randn(n_blocks, KH, page, D).astype(np.float32)
    tables = np.zeros((B, P), np.int32)
    ids = list(range(1, n_blocks))
    if shuffle:
        rng.shuffle(ids)
    for b in range(B):
        for p in range(-(-int(lengths[b]) // page)):
            tables[b, p] = ids.pop()
    return kp, vp, tables


def _quantize(pool):
    """Per-(page, kv head) symmetric int8: d = absmax/127."""
    d = np.abs(pool).max(axis=(2, 3)) / 127.0
    d = np.where(d == 0, 1.0, d)
    q = np.clip(np.round(pool / d[:, :, None, None]), -127,
                127).astype(np.int8)
    return q, d.astype(np.float32)


# ------------------------------------------------------------ kernel


@pytest.mark.parametrize("lengths,page,P", [
    ([1, 8, 7, 19], 8, 4),            # the canonical mixed batch:
])                                    # single-token / boundary /
def test_int8_kernel_parity_ragged(lengths, page, P):   # unaligned /
    """int8 kernel within ATOL of the f32 kernel across the ragged
    length classes, shuffled block ownership."""
    rng = np.random.RandomState(7)
    KH, H, D = 2, 4, 16
    kp, vp, tables = _build_paged(rng, lengths, KH=KH, D=D,
                                  page=page, P=P)
    kq, ks = _quantize(kp)
    vq, vs = _quantize(vp)
    q = rng.randn(len(lengths), H, D).astype(np.float32)
    args = (jnp.asarray(tables), jnp.asarray(lengths, np.int32))
    ref = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), *args,
        interpret=True))
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq), *args,
        k_scales=jnp.asarray(ks), v_scales=jnp.asarray(vs),
        interpret=True))
    assert np.abs(out - ref).max() < ATOL
    # in-register dequant is EXACT vs host-dequantized f32 pools —
    # separates quantization error (bounded above) from kernel error
    deq = np.asarray(paged_attention(
        jnp.asarray(q),
        dequantize_pool(jnp.asarray(kq), jnp.asarray(ks)),
        dequantize_pool(jnp.asarray(vq), jnp.asarray(vs)),
        *args, interpret=True))
    np.testing.assert_allclose(out, np.asarray(deq), rtol=DEQ_TOL,
                               atol=DEQ_TOL)


def test_int8_kernel_gqa_and_dead_rows():
    """Odd GQA grouping (rep=3) and a dead (lengths == 0) row: the
    quantized kernel keeps the f32 kernel's contracts — finite
    everywhere, zeros for the dead row, ATOL parity for the live."""
    rng = np.random.RandomState(11)
    lengths = [9, 0, 4]
    KH, H, D, page, P = 2, 6, 8, 4, 4
    kp, vp, tables = _build_paged(rng, lengths, KH=KH, D=D,
                                  page=page, P=P)
    kq, ks = _quantize(kp)
    vq, vs = _quantize(vp)
    q = rng.randn(3, H, D).astype(np.float32)
    args = (jnp.asarray(tables), jnp.asarray(lengths, np.int32))
    ref = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), *args,
        interpret=True))
    out = np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq), *args,
        k_scales=jnp.asarray(ks), v_scales=jnp.asarray(vs),
        interpret=True))
    assert np.isfinite(out).all()
    assert np.abs(out[1]).max() == 0.0           # dead row: zeros
    assert np.abs(out - ref)[[0, 2]].max() < ATOL


def test_multiquery_kernel_causal_stack():
    """q_tokens > 1 (the speculative verify stack): token t of the
    multi-query dispatch equals a single-token call at lengths + t —
    for both f32 and int8 pools — i.e. the stack is exactly gamma+1
    sequential ragged calls fused into one kernel dispatch."""
    rng = np.random.RandomState(3)
    lengths = np.array([1, 8, 19], np.int32)
    KH, H, D, page, P, S = 2, 4, 16, 8, 4, 3
    kp, vp, tables = _build_paged(rng, lengths, KH=KH, D=D,
                                  page=page, P=P)
    kq, ks = _quantize(kp)
    vq, vs = _quantize(vp)
    qm = rng.randn(len(lengths), S, H, D).astype(np.float32)
    for pools, scales, tol in (
            ((kp, vp), None, 1e-5),
            ((kq, vq), (ks, vs), 1e-5)):
        kw = {} if scales is None else {
            "k_scales": jnp.asarray(scales[0]),
            "v_scales": jnp.asarray(scales[1])}
        stack = np.asarray(paged_attention(
            jnp.asarray(qm), jnp.asarray(pools[0]),
            jnp.asarray(pools[1]), jnp.asarray(tables),
            jnp.asarray(lengths), interpret=True, **kw))
        for t in range(S):
            single = np.asarray(paged_attention(
                jnp.asarray(qm[:, t]), jnp.asarray(pools[0]),
                jnp.asarray(pools[1]), jnp.asarray(tables),
                jnp.asarray(lengths + t), interpret=True, **kw))
            np.testing.assert_allclose(stack[:, t], single, rtol=tol,
                                       atol=tol)


# ----------------------------------------------------- pool numerics


def test_quant_append_rescale_unit():
    """_quant_append keeps every live element within its page scale's
    half-step of the float value, even when later tokens grow the
    page's running max (re-round drift is bounded by one extra
    half-step per rescale; the bound asserted is one FULL step of the
    final scale, 2x headroom over the worst case observed)."""
    rng = np.random.RandomState(0)
    page, KH, D = 8, 2, 4
    pool = jnp.zeros((3, KH, page, D), jnp.int8)
    scales = jnp.zeros((3, KH), jnp.float32)
    # magnitudes GROW so every append rescales — the worst case
    toks = [rng.randn(1, KH, D).astype(np.float32) * (1 + 0.5 * i)
            for i in range(page)]
    bids = np.array([1], np.int32)
    for i, x in enumerate(toks):
        pool, scales = _quant_append(pool, scales, jnp.asarray(bids),
                                     jnp.asarray([i], np.int32),
                                     jnp.asarray(x))
    deq = np.asarray(dequantize_pool(pool, scales))[1]   # (KH, pg, D)
    want = np.concatenate(toks, 0).transpose(1, 0, 2)    # (KH, pg, D)
    step = np.asarray(scales)[1][:, None, None]          # final scale
    assert (np.abs(deq - want) <= step + 1e-7).all()
    # monotone scales: the final scale covers the largest token
    assert (np.asarray(scales)[1] >= np.abs(want).max((1, 2)) / 127.0
            - 1e-7).all()


def test_quant_append_offset0_resets_stale_scale():
    """Pool-reuse regression: free_row returns pages with their last
    owner's scale still in the table (host-only bookkeeping), so the
    FIRST write of a (re)used page — always in-page offset 0 — must
    treat the page as fresh.  A tiny token written at offset 0 of a
    page whose stale scale is huge must quantize at ITS OWN scale,
    not the stale one (which would round it to zero forever, the
    monotone-scale design never recovering)."""
    rng = np.random.RandomState(1)
    page, KH, D = 8, 2, 4
    pool = jnp.zeros((2, KH, page, D), jnp.int8)
    scales = jnp.zeros((2, KH), jnp.float32)
    bids = jnp.asarray([1], jnp.int32)
    big = rng.randn(1, KH, D).astype(np.float32) * 100.0
    pool, scales = _quant_append(pool, scales, bids,
                                 jnp.asarray([0], np.int32),
                                 jnp.asarray(big))
    assert np.asarray(scales)[1].min() > 0.1      # huge page scale
    # ... the row frees; a new row reuses block 1 from offset 0
    small = rng.randn(1, KH, D).astype(np.float32) * 0.01
    pool, scales = _quant_append(pool, scales, bids,
                                 jnp.asarray([0], np.int32),
                                 jnp.asarray(small))
    deq = np.asarray(dequantize_pool(pool, scales))[1][:, 0]  # (KH,D)
    d_own = np.abs(small[0]).max(-1, keepdims=True) / 127.0
    assert (np.abs(deq - small[0]) <= d_own / 2 + 1e-9).all(), \
        "reused page quantized at the stale owner's scale"


@pytest.fixture(scope="module")
def model():
    return CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                           buckets=(16, 32), temp=0.0, seed=1)


def test_commit_roundtrip_error_budget(model):
    """paged_prefill_row into an int8 pool: dequantized pages
    reproduce the f32 pool's pages within d/2 per element (d = that
    page's absmax/127) — the per-page symmetric-quantization error
    budget, measured through the REAL commit program."""
    m = model
    prompt = np.arange(1, 14, dtype=np.int32)
    cf = m.init_paged(2, page=16, kv_dtype="f32")
    ci = m.init_paged(2, page=16, kv_dtype="int8")
    m.paged_prefill_row(cf, prompt, 0)
    m.paged_prefill_row(ci, prompt, 0)
    P = len(prompt)
    for layer in range(m.cfg.layers):
        for pools_f, pools_q, scales in (
                (cf.k_pools, ci.k_pools, ci.k_scales),
                (cf.v_pools, ci.v_pools, ci.v_scales)):
            bid = int(cf.tables[0, 0])
            bid_q = int(ci.tables[0, 0])
            f = np.asarray(pools_f[layer])[bid][:, :P]   # (KH, P, D)
            deq = np.asarray(dequantize_pool(
                pools_q[layer], scales[layer]))[bid_q][:, :P]
            d = np.asarray(scales[layer])[bid_q][:, None, None]
            assert (np.abs(deq - f) <= d / 2 + 1e-7).all(), layer
    cf.reset()
    ci.reset()


def test_int8_paged_decode_token_agreement(model):
    """Greedy chunked paged decode over the int8 pool agrees with the
    f32 paged path token-for-token on the tiny model (>= 75% over 16
    tokens asserted; empirically exact — quantization noise would
    have to flip an argmax to break a token)."""
    m = model
    A = np.arange(1, 8, dtype=np.int32)
    outs = {}
    for kvd in ("f32", "int8"):
        cache = m.init_paged(2, page=16, kv_dtype=kvd)
        lg = m.paged_prefill_row(cache, A, 0)
        out = [int(np.argmax(lg))]
        toks = np.array([out[0], 0], np.int32)
        for _ in range(5):
            blk = m.paged_decode_chunk(cache, toks, 3)
            out += [int(x) for x in blk[0]]
            toks = blk[:, -1].astype(np.int32)
        outs[kvd] = out
        cache.reset()
    agree = np.mean([a == b for a, b in zip(outs["f32"],
                                            outs["int8"])])
    assert outs["f32"][0] == outs["int8"][0]
    assert agree >= 0.75, (agree, outs)


def test_int8_warmup_pins_compile_count(model):
    """The quantized program set (prefill scratch + quantizing commit
    + scale-threading chunk) warms like the float one: a
    join/finish/join cycle after warmup_paged compiles NOTHING new."""
    m = model
    cache = m.init_paged(2, page=16, kv_dtype="int8")
    m.warmup_paged(cache, chunk=4)
    base = m.compile_count()
    assert base > 0
    for prompt in (np.array([1, 2, 3], np.int32),
                   np.arange(1, 12, dtype=np.int32)):
        lg = m.paged_prefill_row(cache, prompt, 0)
        toks = np.array([int(np.argmax(lg)), 0], np.int32)
        m.paged_decode_chunk(cache, toks, 4)
        m.paged_prefill_row(cache, np.array([7, 7], np.int32), 1)
        m.paged_decode_chunk(cache, toks, 4)
        cache.free_row(0)
        cache.free_row(1)
    assert m.compile_count() == base, \
        "quantized paged steady state recompiled on join/finish/join"


def test_pool_bytes_halve(model):
    """device_mb MEASURED from placed buffers: int8 == 1/2 bf16 ==
    1/4 f32 for the same page count (within 10% — the scale arrays
    are the only overhead)."""
    m = model
    mb = {}
    for kvd in ("f32", "bf16", "int8"):
        c = m.init_paged(2, page=16, pool_pages=16, kv_dtype=kvd)
        mb[kvd] = c.device_mb()
        assert c.kv_dtype == kvd and (c.quantized == (kvd == "int8"))
    assert abs(mb["int8"] / mb["bf16"] - 0.5) < 0.1, mb
    assert abs(mb["int8"] / mb["f32"] - 0.25) < 0.1, mb


# ------------------------------------------- sharded int8 (tp mesh)


@pytest.mark.slow
def test_sharded_int8_paged_token_exact(model):
    """int8 pools + tensor parallelism compose: the tp=2-sharded
    quantized paged path (scales sharded on their kv-head axis,
    quantized kernel under shard_map) is token-exact with the
    single-chip int8 paged path at the same seed."""
    from jax.sharding import PartitionSpec
    from libsplinter_tpu.parallel import (ShardedCompletionModel,
                                          make_mesh)

    base = model
    mesh = make_mesh(dp=4, tp=2)
    tp = ShardedCompletionModel(
        DecoderConfig.tiny(dtype=jnp.float32), mesh,
        params=base.params, buckets=(16, 32), temp=0.0, seed=1)
    A = np.arange(1, 8, dtype=np.int32)

    def run(m):
        cache = m.init_paged(2, page=16, kv_dtype="int8")
        if m is tp:
            assert tuple(cache.k_scales[0].sharding.spec) \
                == (None, "tp")
        lg = m.paged_prefill_row(cache, A, 0)
        out = [int(np.argmax(lg))]
        toks = np.array([out[0], 0], np.int32)
        for _ in range(3):
            blk = m.paged_decode_chunk(cache, toks, 3)
            out += [int(x) for x in blk[0]]
            toks = blk[:, -1].astype(np.int32)
        cache.reset()
        return out

    assert run(base) == run(tp)


# --------------------------------------- self-drafting speculative


def test_self_draft_aliases_target(model):
    """self_draft_model shares the target's arrays — zero checkpoint
    bytes: embedding/norm/head and every kept layer are the SAME
    buffers, and the draft is strictly shallower."""
    d = self_draft_model(model, 1)
    tp_, dp_ = model.params["params"], d.params["params"]
    assert dp_["tok_emb"] is tp_["tok_emb"]
    assert dp_["lm_head"] is tp_["lm_head"]
    assert dp_["layer_0"] is tp_["layer_0"]
    assert "layer_1" not in dp_
    assert d.cfg.layers == 1
    with pytest.raises(ValueError):
        self_draft_model(model, model.cfg.layers)   # full depth = no-op


def test_spec_paged_greedy_token_exact(model):
    """Paged speculative decode (drafts verified through the
    multi-query paged kernel) reproduces the target's own greedy
    tokens — BYTE-EXACT over f32 pools; over int8 pools the same
    tolerance as plain int8 decode applies (>= 75% token agreement:
    quantization noise can flip an argmax, and a REJECTED draft's
    stale append may rescale a page the plain path never saw) —
    including a mid-flight joiner, with zero leaked pages after the
    rows free."""
    t = model
    A = np.arange(1, 8, dtype=np.int32)
    Bp = np.array([9, 2, 6], np.int32)
    sa = [int(x) for x in t.generate_tokens(A, 16, chunk=4)]
    t.reset()
    sb = [int(x) for x in t.generate_tokens(Bp, 8, chunk=4)]
    t.reset()
    spec = SpeculativeCompletionModel(t, self_draft_model(t, 1),
                                      gamma=3)
    for kvd in ("f32", "int8"):
        cache = spec.init_paged(2, page=16, kv_dtype=kvd)
        lg = spec.paged_prefill_row(cache, A, 0)
        out_a = [int(np.argmax(lg))]
        pend = spec.paged_decode_chunk_async(
            cache, np.array([out_a[0], -1], np.int64), 5)
        out_a += [int(x) for x in pend.block()[0]]
        # joiner lands mid-decode with its own full context
        jl = spec.paged_prefill_row(cache, Bp, 1)
        out_b = [int(np.argmax(jl))]
        pend = spec.paged_decode_chunk_async(
            cache, np.array([-1, out_b[0]], np.int64), 5,
            carry=pend.last)
        blk = pend.block()
        out_a += [int(x) for x in blk[0]]
        out_b += [int(x) for x in blk[1]]
        pend = spec.paged_decode_chunk_async(
            cache, np.array([-1, -1], np.int64), 5, carry=pend.last)
        blk = pend.block()
        out_a += [int(x) for x in blk[0]]
        out_b += [int(x) for x in blk[1]]
        if kvd == "f32":
            assert out_a[:16] == sa[:16], kvd
            assert out_b[:8] == sb[:8], kvd
        else:
            agree_a = np.mean([x == y for x, y in zip(out_a[:16],
                                                      sa[:16])])
            agree_b = np.mean([x == y for x, y in zip(out_b[:8],
                                                      sb[:8])])
            assert out_a[0] == sa[0] and out_b[0] == sb[0]
            assert agree_a >= 0.5 and agree_b >= 0.5, \
                (agree_a, agree_b)
        cache.free_row(0)
        cache.free_row(1)
        assert cache.used_pages == 0
        assert cache.draft.used_pages == 0
    assert spec.stats_proposed > 0
    assert spec.stats_verified > spec.stats_proposed   # +1 per step


def test_spec_paged_compile_count_pinned(model):
    """The spec-paged program set (both halves' prefill/commit/chunk
    + the fused propose-verify-accept step) pins compile_count flat
    across join/finish/join — the daemon's warmup contract extends to
    the speculative lane."""
    spec = SpeculativeCompletionModel(model, self_draft_model(model, 1),
                                      gamma=3)
    cache = spec.init_paged(2, page=16, kv_dtype="int8")
    spec.warmup_paged(cache, chunk=4)
    base = spec.compile_count()
    assert base > 0
    for prompt in (np.array([1, 2, 3], np.int32),
                   np.arange(1, 12, dtype=np.int32)):
        lg = spec.paged_prefill_row(cache, prompt, 0)
        spec.paged_decode_chunk(
            cache, np.array([int(np.argmax(lg)), -1], np.int64), 4)
        spec.paged_prefill_row(cache, np.array([7, 7], np.int32), 1)
        spec.paged_decode_chunk(cache, np.array([-1, 5], np.int64), 4)
        cache.free_row(0)
        cache.free_row(1)
    assert spec.compile_count() == base, \
        "spec paged steady state recompiled on join/finish/join"


def test_spec_agreement_stats(model):
    """Token-level agreement bookkeeping: greedy self-draft proposals
    agree with the target at a rate the stats expose (acceptance_rate
    = accepted/proposed), and the verify counter tracks one extra
    position per step."""
    spec = SpeculativeCompletionModel(model, self_draft_model(model, 1),
                                      gamma=3)
    out = [int(x) for x in spec.generate_tokens(
        np.arange(1, 8, dtype=np.int32), 16)]
    assert len(out) == 16
    assert spec.stats_proposed > 0
    assert 0.0 <= spec.acceptance_rate <= 1.0
    # g+1 positions scored per <=g drafted (g shrinks at the window
    # tail), so verified strictly exceeds proposed by the step count
    assert spec.stats_proposed < spec.stats_verified \
        <= 2 * spec.stats_proposed
    spec.reset()


@pytest.mark.slow
def test_self_draft_acceptance_beats_floor():
    """The tentpole's acceptance claim at tier scale: a first-3/4-
    layers self-draft on an 8-layer random-weight decoder accepts
    >= 0.3 of proposals under the default sampler (r05's random tiny
    draft measured 0.05 — the demotion floor is 0.2).  Real
    checkpoints only improve on random weights."""
    cfg = DecoderConfig(vocab_size=512, hidden=256, layers=8, heads=8,
                        kv_heads=8, mlp_dim=512, max_len=256,
                        dtype=jnp.float32, flash_min_seq=0)
    t = CompletionModel(cfg, buckets=(64,), temp=0.7, top_p=0.9,
                        seed=0)
    spec = SpeculativeCompletionModel(t, self_draft_model(t, 6),
                                      gamma=4)
    n = sum(1 for _ in spec.generate_tokens(
        np.arange(1, 33, dtype=np.int32), 96))
    assert n == 96
    assert spec.acceptance_rate >= 0.3, spec.acceptance_rate


# --------------------------------------------- daemon (continuous)


def _mkstore(tag):
    from libsplinter_tpu import Store
    name = f"/spt-quantkv-{tag}"
    Store.unlink(name)
    return name, Store.create(name, nslots=128, max_val=4096,
                              vec_dim=8)


def _submit(st, key, prompt):
    from libsplinter_tpu.engine import protocol as P
    st.set(key, prompt)
    st.label_or(key, P.LBL_INFER_REQ)
    st.bump(key)


def _await_ready(st, keys, timeout=90):
    from libsplinter_tpu.engine import protocol as P
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(st.labels(k) & P.LBL_READY for k in keys):
            return True
        time.sleep(0.05)
    return False


def test_continuous_int8_token_exact_and_heartbeat(model):
    """The flagship daemon assertion: --kv-dtype int8 continuous
    serving is byte-identical to the dense drain at the same seed,
    the heartbeat carries kv_dtype + measured pool_mb, and `spt
    metrics` renders them.  (Byte-equality is deterministic per
    environment — fixed seed, greedy, no spec path — and the plain
    int8 argmax margin on this geometry is wide; if a future jax
    bump flips a token here, downgrade to the >= 75% agreement bar
    of test_int8_paged_decode_token_agreement rather than chasing
    bit-parity.)"""
    import contextlib
    import io

    from libsplinter_tpu import Store
    from libsplinter_tpu.engine.completer import Completer

    out = {}
    hb = {}
    for tag in ("dense", "int8"):
        name, st = _mkstore(tag)
        try:
            comp = Completer(st, model=model, max_new_tokens=10,
                             flush_tokens=4, template="none",
                             batch_cap=4, page_size=16,
                             kv_dtype="int8" if tag == "int8"
                             else None)
            comp.attach()
            for i in range(3):
                _submit(st, f"q/{i}", f"say {i} things")
            if tag == "int8":
                th = threading.Thread(
                    target=comp.run_continuous,
                    kwargs=dict(idle_timeout_ms=20, stop_after=90),
                    daemon=True)
                th.start()
                assert _await_ready(st, [f"q/{i}" for i in range(3)])
                comp.stop()
                th.join(timeout=10)
                comp.publish_stats()
                hb = json.loads(st.get("__completer_stats")
                                .rstrip(b"\0"))
                from libsplinter_tpu.cli.main import COMMANDS, Session
                ses = Session(name)
                try:
                    fn, _, _ = COMMANDS["metrics"]
                    buf = io.StringIO()
                    with contextlib.redirect_stdout(buf):
                        fn(ses, [])
                    prom = buf.getvalue()
                finally:
                    ses.close()
            else:
                assert comp.run_once() == 3
            out[tag] = b"|".join(
                st.get(f"q/{i}").rstrip(b"\0") for i in range(3))
        finally:
            st.close()
            Store.unlink(name)
    assert out["dense"] == out["int8"]
    assert hb.get("kv_dtype") == "int8"
    assert hb.get("pool_mb", 0) > 0
    assert hb.get("pages_used") == 0          # all rows freed
    assert 'kv_dtype="int8"' in prom
    assert "sptpu_completer_kv_pool_info" in prom
    assert "sptpu_completer_pool_mb" in prom


@pytest.mark.slow
def test_continuous_spec_serves_paged(model):
    """SpeculativeCompletionModel on the continuous lane: paged_ok is
    True (no more paged_supported=False dead weight), greedy output
    is byte-identical to the plain dense drain, and the heartbeat
    ledgers draft/verify counters without tripping the demotion
    guard when the floor is disabled."""
    from libsplinter_tpu import Store
    from libsplinter_tpu.engine.completer import Completer

    spec = SpeculativeCompletionModel(model, self_draft_model(model, 1),
                                      gamma=3)
    name, st = _mkstore("dense-ref")
    try:
        comp = Completer(st, model=model, max_new_tokens=10,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=16)
        comp.attach()
        for i in range(3):
            _submit(st, f"q/{i}", f"say {i} things")
        assert comp.run_once() == 3
        dense = b"|".join(st.get(f"q/{i}").rstrip(b"\0")
                          for i in range(3))
    finally:
        st.close()
        Store.unlink(name)

    name, st = _mkstore("spec")
    try:
        # f32 pools: byte-equality is the GUARANTEED spec contract
        # over float pools (test_spec_paged_greedy_token_exact); the
        # int8+spec combination carries plain-int8's agreement
        # tolerance and is asserted there, not here — byte-asserting
        # it against a dense f32 drain would flake on legitimate
        # quantization noise
        comp = Completer(st, model=spec, max_new_tokens=10,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=16,
                         spec_min_acceptance=0)   # tiny random draft
        comp.attach()
        assert comp._paged_ok()
        for i in range(3):
            _submit(st, f"q/{i}", f"say {i} things")
        th = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=120),
            daemon=True)
        th.start()
        assert _await_ready(st, [f"q/{i}" for i in range(3)])
        comp.stop()
        th.join(timeout=10)
        got = b"|".join(st.get(f"q/{i}").rstrip(b"\0")
                        for i in range(3))
        comp.publish_stats()
        hb = json.loads(st.get("__completer_stats").rstrip(b"\0"))
    finally:
        st.close()
        Store.unlink(name)
    assert got == dense
    assert hb.get("spec_draft_tokens", 0) > 0
    assert hb.get("spec_verified_tokens", 0) > hb.get(
        "spec_draft_tokens", 0) // 2
    assert comp.stats.spec_demotions == 0


@pytest.mark.slow
def test_continuous_spec_demotes_at_idle(model):
    """The PR-5 demotion guard reaches the continuous lane: with an
    absurd acceptance floor, the heartbeat-cadence check swaps
    self._model to the target and the loop ADOPTS it at the next
    idle point (fresh plain pool) — requests submitted after the
    demotion are served by the plain model, and nothing wedges."""
    from libsplinter_tpu import Store
    from libsplinter_tpu.engine.completer import Completer

    spec = SpeculativeCompletionModel(model, self_draft_model(model, 1),
                                      gamma=3)
    name, st = _mkstore("demote")
    try:
        comp = Completer(st, model=spec, max_new_tokens=10,
                         flush_tokens=4, template="none", batch_cap=4,
                         page_size=16,
                         spec_min_acceptance=0.99)  # cannot be met
        comp.attach()
        assert comp._paged_ok()
        for i in range(3):
            _submit(st, f"q/{i}", f"say {i} things")
        th = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=20, stop_after=120),
            daemon=True)
        th.start()
        assert _await_ready(st, [f"q/{i}" for i in range(3)])
        # wait out heartbeat cadence: the floor check runs every 2 s
        # and needs >= 32 proposals of history behind it
        deadline = time.time() + 30
        while time.time() < deadline \
                and comp.stats.spec_demotions == 0:
            time.sleep(0.25)
        assert comp.stats.spec_demotions >= 1
        # a post-demotion request is served by the adopted target
        _submit(st, "q/after", "one more")
        assert _await_ready(st, ["q/after"], timeout=60)
        assert comp._model is model           # wrapper retired
        comp.stop()
        th.join(timeout=10)
    finally:
        st.close()
        Store.unlink(name)
